"""Causal tracing + round critical-path attribution (ISSUE 9).

Fast tier: trace-context algebra, size-cap rotation of the JSONL sinks,
client -> edge -> server stitching over a real loopback TCP broker (zero
orphan spans, Perfetto flow arrows), trace continuity across a broker
kill/restart (the chaos_smoke [8/8] scenario), the `critical_path` verb
on synthetic streams, and the regress gate's host-overhead ceiling.

Slow tier: a real tiny run emits round_breakdown whose segments cover
the iteration wall, and `critical_path` renders it.

Every blocking operation carries an explicit timeout (test_resilience.py
convention): socket-level scenarios must not wedge the fast tier.
"""

import json
import os
import queue
import time

import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.comm.compress import UpdateReceiver, UpdateSender
from feddrift_tpu.comm.netbroker import NetworkBroker, NetworkBrokerClient
from feddrift_tpu.obs import critical_path, regress, spans
from feddrift_tpu.platform.hierarchical import EdgeRelay
from feddrift_tpu.resilience import ReconnectingBrokerClient, RetryPolicy

E2E_DEADLINE = 60.0


@pytest.fixture()
def bus():
    """Fresh memory-only event bus per test."""
    b = obs.configure(None)
    yield b
    obs.configure(None)


@pytest.fixture()
def run_dir(tmp_path):
    """Arm the process-default span recorder on a run dir; restore the
    disabled library default afterwards so other tests see no spans."""
    d = str(tmp_path / "run")
    os.makedirs(d, exist_ok=True)
    spans.configure(os.path.join(d, "spans.jsonl"))
    yield d
    spans.configure(None)
    spans.get_recorder().enabled = False


def _sync(*clients, timeout=10.0):
    """TCP subscribe is async: loopback one message per client so every
    subscription registered before it is live on the broker."""
    for c in clients:
        q = c.subscribe("__sync__")
        c.publish("__sync__", "ready")
        assert q.get(timeout=timeout) == "ready"


# ----------------------------------------------------------------------
class TestTraceContext:
    def test_child_continues_trace(self):
        root = spans.new_trace()
        child = spans.child_of(root)
        assert child["trace_id"] == root["trace_id"]
        assert child["span_id"] != root["span_id"]
        assert child["parent_span_id"] == root["span_id"]
        grand = spans.child_of(child)
        assert grand["trace_id"] == root["trace_id"]
        assert grand["parent_span_id"] == child["span_id"]

    def test_malformed_context_starts_new_root(self):
        for bad in (None, {}, {"span_id": "x"}, "not-a-dict", 42):
            ctx = spans.child_of(bad)
            assert ctx["trace_id"] and ctx["span_id"]
            assert "parent_span_id" not in ctx

    def test_roots_are_distinct(self):
        a, b = spans.new_trace(), spans.new_trace()
        assert a["trace_id"] != b["trace_id"]


# ----------------------------------------------------------------------
class TestRotation:
    def test_span_sink_rotates_at_cap(self, tmp_path, bus):
        path = str(tmp_path / "spans.jsonl")
        rec = spans.SpanRecorder(path, max_bytes=2000)
        n = 0
        while rec.rotations < 1 and n < 500:
            rec.record("s", time.time(), 0.001, i=n)
            n += 1
        rec.close()
        assert rec.rotations >= 1
        assert os.path.exists(path + ".1")
        # loud boundary marker, carrying the rotated-out size
        rot = [e for e in bus.events() if e["kind"] == "obs_rotated"]
        assert rot and rot[0]["file"] == "spans.jsonl"
        assert rot[0]["rotated_bytes"] >= 2000
        assert rot[0]["generation"] == 1

    def test_no_span_lost_at_rotation_boundary(self, tmp_path, bus):
        """The write that trips the cap lands in the rotated-out file and
        the next one in the fresh file — no record falls in the crack."""
        path = str(tmp_path / "spans.jsonl")
        rec = spans.SpanRecorder(path, max_bytes=600)
        total = 0
        while rec.rotations < 1:
            rec.record("s", time.time(), 0.001, i=total)
            total += 1
        rec.record("s", time.time(), 0.001, i=total)   # first post-rotation
        total += 1
        rec.close()
        rows = []
        for p in (path + ".1", path):
            rows += [json.loads(l) for l in open(p)]
        assert [r["args"]["i"] for r in rows] == list(range(total))

    def test_event_sink_rotates_and_marks(self, tmp_path):
        from feddrift_tpu.obs.events import EventBus
        path = str(tmp_path / "events.jsonl")
        b = EventBus(path, max_bytes=1500)
        n = 0
        while b.rotations < 1 and n < 500:
            b.emit("run_start", i=n)
            n += 1
        assert b.rotations >= 1
        assert os.path.exists(path + ".1")
        # the marker is emitted into the FRESH generation (re-entrant
        # emit after the rotation completes), so it is never rotated away
        kinds = [json.loads(l)["kind"] for l in open(path)]
        assert "obs_rotated" in kinds

    def test_alert_tap_survives_rotation_reentry(self, tmp_path):
        """Regression: when an ``alert_raised`` write trips the size cap,
        the bus re-entrantly emits ``obs_rotated`` and taps the
        AlertMonitor back on the same thread while its lock is still
        held. With a non-reentrant lock this deadlocked a real run; the
        monitor must use an RLock. Run in a daemon thread so a
        regression fails the assert instead of hanging pytest."""
        import threading

        from feddrift_tpu.obs.alerts import AlertMonitor
        from feddrift_tpu.obs.events import EventBus
        b = EventBus(str(tmp_path / "events.jsonl"), max_bytes=1200)
        mon = AlertMonitor(path=None).attach(b)

        def pump():
            # each client_killed fires the client_outage rule (cooldown 1,
            # iteration advances every emit), so alert_raised writes keep
            # landing until one trips the rotation mid-tap
            for i in range(200):
                b.emit("client_killed", iteration=i, client=i)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), "alert tap deadlocked on rotation re-entry"
        assert b.rotations >= 1
        assert mon.alerts

    def test_build_trace_folds_rotated_generation(self, tmp_path, bus):
        d = str(tmp_path)
        path = os.path.join(d, "spans.jsonl")
        rec = spans.SpanRecorder(path, max_bytes=600)
        total = 0
        while rec.rotations < 1:
            rec.record("s", time.time(), 0.001, i=total)
            total += 1
        for _ in range(3):
            rec.record("s", time.time(), 0.001, i=total)
            total += 1
        rec.close()
        tr = spans.build_trace(d)
        xs = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == total
        assert sorted(e["args"]["i"] for e in xs) == list(range(total))

    def test_uncapped_recorder_never_rotates(self, tmp_path, bus):
        path = str(tmp_path / "spans.jsonl")
        rec = spans.SpanRecorder(path)          # max_bytes=0: unbounded
        for i in range(300):
            rec.record("s", time.time(), 0.001, i=i)
        rec.close()
        assert rec.rotations == 0
        assert not os.path.exists(path + ".1")
        assert not [e for e in bus.events() if e["kind"] == "obs_rotated"]


# ----------------------------------------------------------------------
class TestWireStitching:
    def test_zero_orphans_and_client_edge_server_flows(self, run_dir, bus):
        """The acceptance smoke: a two-tier (E=3) exchange over the real
        TCP broker — one client per edge, each EdgeRelay forwarding its
        summary to the server. Every edge's chain client -> edge ->
        server shares one trace_id, every parent_span_id resolves to a
        recorded span (zero orphans), and the exported trace.json
        connects the hops with Perfetto flow arrows."""
        E = 3
        broker = NetworkBroker()
        clients = []

        def _client():
            c = NetworkBrokerClient(broker.host, broker.port)
            clients.append(c)
            return c

        try:
            rx_srv = UpdateReceiver(_client(), "fl/up")
            relays, txs = [], []
            for e in range(E):
                down = f"fl/e{e}/down"
                rx_down = UpdateReceiver(_client(), down)
                tx_up = UpdateSender(_client(), "fl/up", codec="none")
                relays.append(EdgeRelay(rx_down, tx_up, edge_id=e))
                txs.append(UpdateSender(_client(), down, codec="none"))
            _sync(*clients)

            for e in range(E):
                txs[e].send(f"w{e}", np.arange(8, dtype=np.float32) + e)
                assert relays[e].relay_round(1, timeout=10.0) is not None
            summaries = [rx_srv.recv(timeout=10.0) for _ in range(E)]
            assert all(s is not None and s[0] == "edge_summary"
                       for s in summaries)
        finally:
            for c in clients:
                c.close()
            broker.close()

        recorded = [s for s in spans.get_recorder().spans()
                    if s.get("args", {}).get("span_id")]
        by_name = {}
        for s in recorded:
            by_name.setdefault(s["name"], []).append(s)
        # every hop of every chain made it onto the timeline
        for hop in ("send_update", "recv_update", "broker_publish",
                    "broker_deliver"):
            assert by_name.get(hop), f"missing {hop} span"
        assert len(by_name["send_update"]) == 2 * E   # clients + edges
        assert len(by_name["recv_update"]) == 2 * E   # edges + server

        # one trace per client update, threaded end to end: each root
        # send (no parent) is continued by exactly 3 more update hops
        # (edge recv, edge send, server recv)
        roots = [s for s in by_name["send_update"]
                 if "parent_span_id" not in s["args"]]
        assert len(roots) == E
        for root in roots:
            tid = root["args"]["trace_id"]
            chain = [s for s in by_name["send_update"]
                     + by_name["recv_update"]
                     if s["args"]["trace_id"] == tid]
            assert len(chain) == 4, \
                f"trace {tid} not threaded client->edge->server: {chain}"

        # zero orphan spans: every parent link resolves
        ids = {s["args"]["span_id"] for s in recorded}
        for s in recorded:
            parent = s["args"].get("parent_span_id")
            assert parent is None or parent in ids, \
                f"orphan span {s['name']}: parent {parent} unrecorded"

        # the exported trace.json carries flow arrows bound to slices
        d = os.path.dirname(spans.get_recorder().path)
        spans.get_recorder().close()
        trace_path = spans.write_trace(d)
        evs = json.load(open(trace_path))["traceEvents"]
        starts = [e for e in evs if e.get("ph") == "s"]
        finishes = [e for e in evs if e.get("ph") == "f"]
        assert len(starts) >= 3 * E and len(starts) == len(finishes)
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        for e in starts + finishes:
            assert e["cat"] == "trace"

    def test_trace_survives_broker_reconnect(self, run_dir, bus):
        """chaos_smoke [8/8]: a frame published through the reconnect
        layer keeps its trace context across a broker kill/restart — the
        resent frame carries the same trace_id, so the causal chain stays
        connected through the outage."""
        broker = NetworkBroker()
        host, port = broker.host, broker.port
        cli = ReconnectingBrokerClient(
            lambda: NetworkBrokerClient(host, port),
            retry=RetryPolicy(base_delay=0.05, max_delay=0.2,
                              max_attempts=60, deadline_s=30, seed=0),
            ack_timeout=0.2)
        broker2 = None
        try:
            q = cli.subscribe("t")
            ctx0 = spans.new_trace()
            cli.publish("t", "before", trace=ctx0)
            assert q.get(timeout=10.0) == "before"

            broker.close()                        # broker dies
            time.sleep(0.2)
            ctx1 = spans.new_trace()
            cli.publish("t", "while-down", trace=ctx1)   # buffered
            broker2 = NetworkBroker(host=host, port=port)
            got = set()
            end = time.monotonic() + E2E_DEADLINE
            while "while-down" not in got and time.monotonic() < end:
                try:
                    got.add(q.get(timeout=0.25))
                except queue.Empty:
                    pass
            assert "while-down" in got           # replayed after reconnect
            assert cli.reconnects >= 1
        finally:
            cli.close()
            broker.close()
            if broker2 is not None:
                broker2.close()

        # the delivered resend still carried ctx1's trace: its
        # broker_deliver span continues the same trace_id
        def _by_trace(name, tid):
            return [s for s in spans.get_recorder().spans(name)
                    if s.get("args", {}).get("trace_id") == tid]
        end = time.monotonic() + 10.0
        while not _by_trace("broker_deliver", ctx1["trace_id"]) \
                and time.monotonic() < end:
            time.sleep(0.05)
        assert _by_trace("broker_publish", ctx1["trace_id"])
        assert _by_trace("broker_deliver", ctx1["trace_id"]), \
            "resent frame lost its trace context across the reconnect"
        # and the pre-outage publish kept its own, distinct chain
        assert _by_trace("broker_deliver", ctx0["trace_id"])


# ----------------------------------------------------------------------
def _write_run(tmp_path, walls, breakdown_iters=None, stragglers=(),
               edge_fails=()):
    """Synthetic run dir: iteration spans (µs trace-event units) +
    round_breakdown / fault events whose segments sum to the wall."""
    d = str(tmp_path / "run")
    os.makedirs(d, exist_ok=True)
    t0 = 1_700_000_000.0
    with open(os.path.join(d, "spans.jsonl"), "w") as f:
        for it, wall in enumerate(walls):
            f.write(json.dumps({
                "name": "iteration", "cat": "runner",
                "ts": round(t0 * 1e6, 1), "dur": round(wall * 1e6, 1),
                "pid": 0, "tid": 1, "args": {"iteration": it}}) + "\n")
            t0 += wall
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for it, wall in enumerate(walls):
            if breakdown_iters is not None and it not in breakdown_iters:
                continue
            segs = {"dispatch": round(0.1 * wall, 6),
                    "device_compute": round(0.6 * wall, 6),
                    "writeback": round(0.1 * wall, 6),
                    "dispatch_gap": round(0.2 * wall, 6)}
            f.write(json.dumps({
                "_ts": 1_700_000_000.0, "kind": "round_breakdown",
                "iteration": it, "wall_s": wall, "rounds": 4,
                "profiled_rounds": 4, "segments": segs,
                "dispatch_gap_s": segs["dispatch_gap"],
                "host_overhead_frac": 0.4}) + "\n")
        for it in stragglers:
            f.write(json.dumps({
                "_ts": 1_700_000_000.0, "kind": "straggler_masked",
                "iteration": it, "part_round": 3, "clients": [5, 9],
                "on_time": 8, "deadline": 2.0}) + "\n")
        for it in edge_fails:
            f.write(json.dumps({
                "_ts": 1_700_000_000.0, "kind": "edge_failed",
                "iteration": it, "fault_round": 2, "edges": [0],
                "reason": "killed"}) + "\n")
    return d


class TestCriticalPath:
    def test_segments_cover_iteration_wall(self, tmp_path):
        d = _write_run(tmp_path, [1.0, 1.0, 1.0])
        out = critical_path.analyze(d)
        assert len(out["iterations"]) == 3
        for row in out["iterations"]:
            assert abs(row["coverage"] - 1.0) <= 0.05
        assert out["dominant_segment"] == "device_compute"
        assert out["host_overhead_frac_mean"] == pytest.approx(0.4)

    def test_straggler_attribution_on_extended_iteration(self, tmp_path):
        d = _write_run(tmp_path, [1.0, 1.0, 2.0], stragglers=(2,))
        out = critical_path.analyze(d)
        rows = {r["iteration"]: r for r in out["iterations"]}
        assert not rows[0]["extended"] and not rows[1]["extended"]
        assert rows[2]["extended"]
        assert "straggler client(s) [5, 9]" in rows[2]["attribution"]
        assert "round 3" in rows[2]["attribution"]

    def test_edge_failure_attribution(self, tmp_path):
        d = _write_run(tmp_path, [1.0, 2.5, 1.0], edge_fails=(1,))
        out = critical_path.analyze(d)
        row = [r for r in out["iterations"] if r["iteration"] == 1][0]
        assert row["extended"]
        assert "edge(s) [0] failed (killed)" in row["attribution"]

    def test_extension_without_fault_is_named_variance(self, tmp_path):
        d = _write_run(tmp_path, [1.0, 1.0, 3.0])
        out = critical_path.analyze(d)
        row = [r for r in out["iterations"] if r["iteration"] == 2][0]
        assert row["attribution"] == "no fault recorded (host-side variance)"

    def test_breakdown_event_alone_suffices(self, tmp_path):
        """A run dir whose spans.jsonl was rotated away still renders —
        wall falls back to the event's own wall_s."""
        d = _write_run(tmp_path, [1.0, 1.0])
        os.remove(os.path.join(d, "spans.jsonl"))
        out = critical_path.analyze(d)
        assert len(out["iterations"]) == 2
        assert all(abs(r["coverage"] - 1.0) <= 0.05
                   for r in out["iterations"])

    def test_render_names_critical_path(self, tmp_path, capsys):
        d = _write_run(tmp_path, [1.0, 1.0])
        assert critical_path.main([d]) == 0
        out = capsys.readouterr().out
        assert "critical path: device_compute dominates" in out
        assert "host_overhead_frac (mean): 0.4000" in out

    def test_cli_verb_routes(self, tmp_path, capsys):
        from feddrift_tpu.cli import main
        d = _write_run(tmp_path, [1.0, 1.0])
        assert main(["critical_path", d]) == 0
        capsys.readouterr()
        assert main(["critical_path", d, "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["dominant_segment"] == "device_compute"

    def test_missing_run_dir_exits_2(self, tmp_path, capsys):
        assert critical_path.main([str(tmp_path / "nope")]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        (empty / "events.jsonl").write_text("")
        assert critical_path.main([str(empty)]) == 2


# ----------------------------------------------------------------------
def _bench_fixture(value=100.0, wall=10.0, rounds=1000, acc=0.86,
                   host_overhead=None):
    d = {"value": value, "wall_s": wall, "rounds": rounds,
         "final_test_acc": acc,
         "instruments": {'jit_compiles{fn="train_round"}': 3.0,
                         'jit_recompiles{fn="train_round"}': 0.0}}
    if host_overhead is not None:
        d["host_overhead_frac"] = host_overhead
    return d


def _write(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
    return str(path)


class TestRegressHostOverhead:
    def test_overhead_past_ceiling_fails(self, tmp_path, capsys):
        base = _write(tmp_path / "b.json", _bench_fixture(host_overhead=0.2))
        cand = _write(tmp_path / "c.json", _bench_fixture(host_overhead=0.5))
        assert regress.main([cand, "--baseline", base]) == 1
        assert "host_overhead_frac" in capsys.readouterr().out

    def test_tolerance_waives(self, tmp_path):
        base = _write(tmp_path / "b.json", _bench_fixture(host_overhead=0.2))
        cand = _write(tmp_path / "c.json", _bench_fixture(host_overhead=0.5))
        assert regress.main([cand, "--baseline", base,
                             "--tol-host-overhead", "0.35"]) == 0

    def test_within_default_tolerance_passes(self, tmp_path):
        base = _write(tmp_path / "b.json", _bench_fixture(host_overhead=0.2))
        cand = _write(tmp_path / "c.json", _bench_fixture(host_overhead=0.25))
        assert regress.main([cand, "--baseline", base]) == 0

    def test_missing_field_skips_not_fails(self, tmp_path, capsys):
        """Artifacts predating ISSUE 9 carry no host_overhead_frac: the
        row is skipped so old baselines stay comparable."""
        base = _write(tmp_path / "b.json", _bench_fixture())
        cand = _write(tmp_path / "c.json", _bench_fixture(host_overhead=0.9))
        assert regress.main([cand, "--baseline", base]) == 0


# ----------------------------------------------------------------------
@pytest.mark.slow
class TestRoundBreakdownEndToEnd:
    def test_tiny_run_breakdown_covers_wall(self, tmp_path, capsys):
        """A real run emits round_breakdown whose segments close the
        iteration wall budget, and the critical_path verb renders it."""
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment

        d = str(tmp_path / "run")
        cfg = ExperimentConfig(
            dataset="sea", model="fnn", concept_drift_algo="win-1",
            train_iterations=2, comm_round=2, epochs=1, sample_num=16,
            batch_size=8, client_num_in_total=4, client_num_per_round=4,
            concept_num=2, frequency_of_the_test=1, report_client=0,
            chunk_rounds=False, trace_sync=True, out_dir=d)
        exp = Experiment(cfg, out_dir=d)
        exp.run()

        evs = [json.loads(l) for l in open(os.path.join(d, "events.jsonl"))]
        bds = [e for e in evs if e["kind"] == "round_breakdown"]
        assert len(bds) == 2
        for bd in bds:
            seg_sum = sum(bd["segments"].values())
            assert seg_sum == pytest.approx(bd["wall_s"], rel=0.05)
            assert bd["segments"]["device_compute"] > 0
            assert 0.0 <= bd["host_overhead_frac"] <= 1.0
            assert bd["profiled_rounds"] == bd["rounds"]   # trace_sync
        assert exp.last_round_breakdown["iteration"] == 1

        # the gauge + histogram landed in the registry
        snap = obs.registry().snapshot()
        assert "host_overhead_frac" in snap
        assert any(k.startswith("round_wall_seconds") for k in snap)

        assert critical_path.main([d]) == 0
        out = capsys.readouterr().out
        assert "critical path:" in out
        result = critical_path.analyze(d)
        for row in result["iterations"]:
            assert abs(row["coverage"] - 1.0) <= 0.05
