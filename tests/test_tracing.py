"""Tracing subsystem tests."""

import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


class TestPhaseTracer:
    def test_accumulates(self):
        from feddrift_tpu.utils.tracing import PhaseTracer
        tr = PhaseTracer()
        for _ in range(3):
            with tr.phase("a"):
                time.sleep(0.01)
        with tr.phase("b"):
            pass
        s = tr.summary()
        assert s["a"]["count"] == 3 and s["a"]["total_s"] >= 0.03
        assert s["b"]["count"] == 1
        assert abs(s["a"]["mean_s"] - s["a"]["total_s"] / 3) < 1e-9
        tr.reset()
        assert tr.summary() == {}

    def test_exception_still_recorded(self):
        from feddrift_tpu.utils.tracing import PhaseTracer
        tr = PhaseTracer()
        try:
            with tr.phase("boom"):
                raise RuntimeError
        except RuntimeError:
            pass
        assert tr.summary()["boom"]["count"] == 1

    def test_runner_integration(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment
        cfg = ExperimentConfig(dataset="sea", model="fnn",
                               concept_drift_algo="win-1",
                               train_iterations=1, comm_round=2, epochs=1,
                               sample_num=16, batch_size=8,
                               client_num_in_total=4, client_num_per_round=4,
                               concept_num=2, frequency_of_the_test=1)
        exp = Experiment(cfg)
        exp.run_iteration(0)
        s = exp.last_phase_summary
        # fused path: the whole iteration is ONE device program, evals fetched
        # in one bulk transfer
        assert s["train_round"]["count"] == 1
        assert s["eval"]["count"] == 1
        assert s["cluster"]["count"] == 2   # begin + end
        assert all(np.isfinite(v["total_s"]) for v in s.values())
        # per-iteration deltas: tracer resets between iterations
        assert exp.tracer.summary() == {}

        # per-round path: one train_round/eval phase per round
        from dataclasses import replace
        exp2 = Experiment(replace(cfg, chunk_rounds=False))
        exp2.run_iteration(0)
        s2 = exp2.last_phase_summary
        assert s2["train_round"]["count"] == 2
        assert s2["eval"]["count"] == 2


class TestAnnotate:
    def test_annotation_context(self):
        import jax.numpy as jnp
        from feddrift_tpu.utils.tracing import annotate
        with annotate("region"):
            x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0
