"""Multi-iteration megastep: K fused time steps per device dispatch.

The megastep (TrainStep.train_megastep, runner.run_megastep) scans K
whole time steps — each itself an R-round fused scan with scheduled
evals — inside ONE device program, so the host touches the device once
per K iterations instead of once per iteration. The contract under test:

- bitwise parity: the K>1 path must reproduce the K=1 driver exactly
  (params, eval series, decision trajectories) — same fold_in key
  sequence, same opt-state reinit, same eval cadence;
- validity gating: ``_megastep_span`` fuses only configurations the scan
  actually models, and ``megastep_horizon`` clamps the span at the next
  drift-decision boundary;
- compile stability: one program per K, compiled once — steady-state
  blocks must hit the jit cache (the perf win evaporates otherwise);
- the regress gate's megastep axis (rounds/s floor, absolute
  zero-recompile, host-overhead-beats-K=1).
"""

import jax
import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment


def _cfg(**kw):
    base = dict(dataset="sea", model="lr", concept_drift_algo="oblivious",
                concept_drift_algo_arg="", concept_num=1,
                client_num_in_total=8, client_num_per_round=8,
                train_iterations=8, comm_round=5, epochs=1, batch_size=50,
                sample_num=50, frequency_of_the_test=5, lr=0.05,
                seed=7, trace_sync=True)
    base.update(kw)
    return ExperimentConfig(**base)


def _leafdiff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
               for x, y in zip(la, lb))


@pytest.mark.slow
class TestMegastepParity:
    """K=4 vs K=1 must be bitwise-identical end to end."""

    def _pair(self, **kw):
        return run_experiment(_cfg(megastep_k=1, **kw)), \
               run_experiment(_cfg(megastep_k=4, **kw))

    def test_oblivious_bitwise(self):
        e1, e4 = self._pair()
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        assert e1.logger.series("Train/Acc") == e4.logger.series("Train/Acc")

    def test_softcluster_cadence_bitwise(self):
        # cadence-3 softcluster: decisions at t=0,3,6 — the megastep fuses
        # the decision-free gaps and the carried-forward weight trajectory
        # must match the sequential driver exactly
        kw = dict(concept_drift_algo="softcluster",
                  concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
                  decision_cadence=3)
        e1, e4 = self._pair(**kw)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        assert np.array_equal(e1.algo.weights, e4.algo.weights)

    def test_partial_participation_bitwise(self):
        # per-round client masks ride through the scan as a [K, R, C] xs
        e1, e4 = self._pair(client_num_per_round=2)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def _assert_fused_once(self, exp):
        # non-vacuous: the K=4 run must actually have dispatched the
        # megastep program, with ONE argument signature (zero steady-state
        # recompiles — _cache_size is class-global, signatures are not)
        assert "train_megastep" in exp.step._signatures
        assert len(exp.step._signatures["train_megastep"]) == 1

    def test_population_cohorts_bitwise(self):
        # cohort gathers ride the scan as stacked [K, C, T1, ...] inputs;
        # churn + straggler chaos exercises the full registry bookkeeping
        kw = dict(population_size=40, cohort_size=8, cohort_overprovision=2,
                  straggler_prob=0.1, churn_leave_prob=0.02,
                  churn_join_prob=0.04)
        e1, e4 = self._pair(**kw)
        self._assert_fused_once(e4)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        # registry bookkeeping committed at the block boundary must land
        # exactly where the per-iteration path put it
        for attr in ("active", "joined_round", "last_seen_round",
                     "last_sampled_round", "absent_streak", "reliability",
                     "cluster"):
            assert np.array_equal(getattr(e1.registry, attr),
                                  getattr(e4.registry, attr)), attr

    def test_population_resume_identical_cohorts(self, tmp_path):
        # a kill after the first fused block must resume onto the exact
        # cohort schedule the uninterrupted run draws
        import json, os
        kw = dict(population_size=40, cohort_size=8, cohort_overprovision=2,
                  straggler_prob=0.1, churn_leave_prob=0.02,
                  churn_join_prob=0.04, megastep_k=4,
                  checkpoint_every_iteration=True)

        def cohorts(d):
            out = {}
            with open(os.path.join(d, "events.jsonl")) as f:
                for line in f:
                    e = json.loads(line)
                    if e.get("kind") == "cohort_sampled":
                        out.setdefault(e["iteration"], e["members"])
            return out

        d_full = str(tmp_path / "full")
        e_full = Experiment(_cfg(**kw), out_dir=d_full)
        e_full.run()
        d_part = str(tmp_path / "part")
        e_part = Experiment(_cfg(**kw), out_dir=d_part)
        with e_part.logger, e_part.events:
            done = e_part.run_megastep(0, e_part._megastep_span(0))
        assert done == 4           # "killed" after the first block
        e_res = Experiment.resume(_cfg(**kw), d_part)
        assert e_res.start_iteration == 4
        e_res.run()
        assert cohorts(d_part) == cohorts(d_full)
        assert _leafdiff(e_full.pool.params, e_res.pool.params) == 0.0

    def test_hierarchy_e3_bitwise(self):
        e1, e4 = self._pair(hierarchy_edges=3,
                            edge_robust_agg="trimmed_mean")
        self._assert_fused_once(e4)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_byzantine_sign_flip_bitwise(self):
        e1, e4 = self._pair(byzantine_clients="0,3",
                            robust_agg="trimmed_mean")
        self._assert_fused_once(e4)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_byzantine_stale_replay_bitwise(self):
        # stale_replay threads a per-round submissions carry; the scan
        # re-seeds it per step exactly like the per-iteration reset
        e1, e4 = self._pair(byzantine_clients="0,3",
                            byzantine_mode="stale_replay",
                            robust_agg="trimmed_mean")
        self._assert_fused_once(e4)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_codec_int8_bitwise(self):
        e1, e4 = self._pair(compress_codec="int8")
        self._assert_fused_once(e4)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_codec_delta_bitwise_all_paths(self):
        # delta codec's carry re-seeds per scanned step; parity must hold
        # against BOTH K=1 drivers — the fused single-iteration program
        # and the per-round host loop
        kw = dict(compress_codec="delta")
        e1, e4 = self._pair(**kw)
        self._assert_fused_once(e4)
        er = run_experiment(_cfg(megastep_k=1, chunk_rounds=False, **kw))
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert _leafdiff(er.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        assert er.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_single_compile_across_blocks(self):
        # 8 iterations at K=4 = two blocks; block 2's params are scan
        # outputs (committed NamedSharding) — the init-time pool placement
        # must make block 1 present the same signature, or every steady
        # block silently recompiles the whole program. (_cache_size is
        # per jit-wrapped function, shared by every TrainStep via the
        # static self argnum — so assert NO GROWTH past block 1, not an
        # absolute count.)
        exp = Experiment(_cfg(megastep_k=4))
        t = exp.run_megastep(0, exp._megastep_span(0))
        n0 = exp.step._train_megastep_jit._cache_size()
        while t < exp.cfg.train_iterations:
            t += exp.run_megastep(t, exp._megastep_span(t))
        assert exp.step._train_megastep_jit._cache_size() == n0


class TestMegastepGate:
    """_megastep_span: fuse only what the scan models, clamp at decision
    boundaries and the end of the run."""

    def test_span_and_tail_clamp(self):
        exp = Experiment(_cfg(megastep_k=4))
        assert exp._megastep_span(0) == 4
        assert exp._megastep_span(6) == 2      # train_iterations=8 tail
        assert exp._megastep_span(7) == 1

    def test_k1_and_unfusable_configs_stay_sequential(self):
        assert Experiment(_cfg(megastep_k=1))._megastep_span(0) == 1
        assert Experiment(
            _cfg(megastep_k=4, chunk_rounds=False))._megastep_span(0) == 1

    def test_feature_configs_fuse(self):
        # the per-feature capability table: codecs, Byzantine schedules,
        # hierarchy and population cohorts all ride the outer scan now
        assert Experiment(
            _cfg(megastep_k=4, compress_codec="topk"))._megastep_span(0) == 4
        assert Experiment(
            _cfg(megastep_k=4, compress_codec="delta"))._megastep_span(0) == 4
        assert Experiment(
            _cfg(megastep_k=4, byzantine_clients="0,3",
                 robust_agg="trimmed_mean"))._megastep_span(0) == 4
        assert Experiment(
            _cfg(megastep_k=4, hierarchy_edges=3))._megastep_span(0) == 4
        assert Experiment(
            _cfg(megastep_k=4, population_size=40, cohort_size=8,
                 cohort_overprovision=2))._megastep_span(0) == 4

    def test_gated_event_and_counter_name_the_reason(self):
        exp = Experiment(_cfg(megastep_k=4, chunk_rounds=False))
        assert exp._megastep_span(0) == 1
        gated = [e for e in exp.events.ring if e["kind"] == "megastep_gated"]
        assert gated and gated[-1]["reason"] == "chunk_rounds_off"
        assert gated[-1]["requested"] == 4 and gated[-1]["granted"] == 1

    def test_horizon_clamp_emits_algo_horizon(self):
        exp = Experiment(_cfg(
            megastep_k=4, concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
            decision_cadence=3))
        assert exp._megastep_span(0) == 3
        gated = [e for e in exp.events.ring if e["kind"] == "megastep_gated"]
        assert gated and gated[-1]["reason"] == "algo_horizon"
        assert gated[-1]["granted"] == 3

    def test_k1_and_tail_clamp_stay_silent(self):
        # K=1 forfeits nothing (fusion never requested); the end-of-run
        # tail clamp is arithmetic, not a feature gate
        exp = Experiment(_cfg(megastep_k=1, chunk_rounds=False))
        assert exp._megastep_span(0) == 1
        exp2 = Experiment(_cfg(megastep_k=4))
        assert exp2._megastep_span(6) == 2
        for e in (exp, exp2):
            assert not [r for r in e.events.ring
                        if r["kind"] == "megastep_gated"]

    def test_horizon_window_stretches_full_tail(self):
        exp = Experiment(_cfg(megastep_k=4, concept_drift_algo="win-1"))
        assert exp.algo.megastep_horizon(0) == 8
        assert exp.algo.megastep_horizon(5) == 3

    def test_horizon_softcluster_cadence(self):
        exp = Experiment(_cfg(
            megastep_k=4, concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
            decision_cadence=3))
        # step t may itself decide; the horizon reaches the NEXT boundary
        assert exp.algo.megastep_horizon(0) == 3
        assert exp.algo.megastep_horizon(1) == 2
        assert exp.algo.megastep_horizon(2) == 1
        assert exp.algo.megastep_horizon(3) == 3
        assert exp._megastep_span(0) == 3      # clamped below megastep_k
        assert exp._megastep_span(1) == 2

    def test_horizon_cadence_one_never_fuses(self):
        exp = Experiment(_cfg(
            megastep_k=4, concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3))
        assert exp.algo.megastep_horizon(2) == 1
        assert exp._megastep_span(2) == 1

    def test_horizon_conservative_default(self):
        from feddrift_tpu.algorithms.base import DriftAlgorithm
        # the base contract: algorithms that don't certify decision-free
        # stretches inherit no fusion at all
        assert DriftAlgorithm.megastep_horizon.__get__(object())(5) == 1


class TestOfferCacheAliasing:
    """offer_acc_matrix hands the SAME ndarray to every consumer; the
    frozen-array + identity-key + rebind-invalidation trio keeps one
    consumer's mutation (or a dataset swap) from corrupting the rest."""

    def test_offered_matrix_is_frozen(self):
        exp = Experiment(_cfg())
        m = np.full((exp.pool.num_models, exp.algo.C), 0.5, np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        got = exp.algo.acc_matrix_at(0)
        assert got is not m or not got.flags.writeable
        with pytest.raises(ValueError):
            got[0, 0] = 0.0

    def test_rebind_invalidates_offer(self):
        exp = Experiment(_cfg())
        m = np.full((exp.pool.num_models, exp.algo.C), 0.5, np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        exp.algo.rebind_data(exp.x, exp.y)
        assert exp.algo._acc_offer is None

    def test_pool_mutation_misses_cache(self):
        exp = Experiment(_cfg())
        m = np.zeros((exp.pool.num_models, exp.algo.C), np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        # any writeback rebinds pool.params to a new object: identity key
        exp.pool.params = jax.tree_util.tree_map(lambda l: l + 0,
                                                 exp.pool.params)
        fresh = exp.algo.acc_matrix_at(0)
        assert fresh is not m and float(fresh.max()) > 0.0


class TestMegastepRegressAxis:
    def test_floor_zero_recompile_and_host_overhead_gates(self):
        from feddrift_tpu.obs.regress import compare
        base = {"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.8},
            {"megastep_k": 4, "rounds_per_sec": 160.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.3}]}
        ok = compare({"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 95.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.82},
            {"megastep_k": 4, "rounds_per_sec": 150.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.35}]}, base)
        ms = {r["metric"]: r for r in ok
              if r["metric"].startswith("megastep")}
        assert ms["megastep[4].rounds_per_s"]["status"] == "ok"
        assert ms["megastep[4].steady_recompiles"]["status"] == "ok"
        assert ms["megastep[4].host_overhead_frac"]["status"] == "ok"
        bad = compare({"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.5},
            {"megastep_k": 4, "rounds_per_sec": 50.0,
             "steady_recompiles": 1, "host_overhead_frac": 0.6}]}, base)
        ms = {r["metric"]: r for r in bad
              if r["metric"].startswith("megastep")}
        assert ms["megastep[4].rounds_per_s"]["status"] == "regress"
        # absolute gates: any recompile, or K>1 overhead >= this run's K=1
        assert ms["megastep[4].steady_recompiles"]["status"] == "regress"
        assert ms["megastep[4].host_overhead_frac"]["status"] == "regress"

    def test_pop_hier_variant_keys_and_absolute_speedup_gate(self):
        from feddrift_tpu.obs.regress import compare
        # composed-variant rows get megastep[pop_hier:{k}] keys, their own
        # K=1 host-overhead reference, and an ABSOLUTE >= 2x speedup gate
        base = {"megastep": [
            {"variant": "pop_hier", "megastep_k": 1, "rounds_per_sec": 20.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.9,
             "speedup_vs_k1": 1.0},
            {"variant": "pop_hier", "megastep_k": 4, "rounds_per_sec": 50.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.4,
             "speedup_vs_k1": 2.5}]}
        ok = compare({"megastep": [
            {"variant": "pop_hier", "megastep_k": 1, "rounds_per_sec": 19.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.9,
             "speedup_vs_k1": 1.0},
            {"variant": "pop_hier", "megastep_k": 4, "rounds_per_sec": 48.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.45,
             "speedup_vs_k1": 2.53}]}, base)
        ms = {r["metric"]: r for r in ok
              if r["metric"].startswith("megastep")}
        assert ms["megastep[pop_hier:4].rounds_per_s"]["status"] == "ok"
        assert ms["megastep[pop_hier:4].speedup_vs_k1"]["status"] == "ok"
        assert ms["megastep[pop_hier:4].host_overhead_frac"]["status"] == "ok"
        bad = compare({"megastep": [
            {"variant": "pop_hier", "megastep_k": 1, "rounds_per_sec": 20.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.9,
             "speedup_vs_k1": 1.0},
            {"variant": "pop_hier", "megastep_k": 4, "rounds_per_sec": 36.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.5,
             "speedup_vs_k1": 1.8}]}, base)
        ms = {r["metric"]: r for r in bad
              if r["metric"].startswith("megastep")}
        # absolute: below 2x fails even though the baseline's 2.5 would
        # tolerate it under a relative check
        assert ms["megastep[pop_hier:4].speedup_vs_k1"]["status"] == "regress"

    def test_variantless_baseline_is_dense_backcompat(self):
        from feddrift_tpu.obs.regress import compare
        # MEGASTEP_r10 rows carry no "variant": they must keep matching
        # bare-keyed dense candidate rows, and a pop_hier candidate row
        # must NOT silently match a dense baseline K point
        base = {"megastep": [
            {"megastep_k": 4, "rounds_per_sec": 160.0,
             "steady_recompiles": 0}]}
        rows = compare({"megastep": [
            {"variant": "dense", "megastep_k": 4, "rounds_per_sec": 155.0,
             "steady_recompiles": 0},
            {"variant": "pop_hier", "megastep_k": 4, "rounds_per_sec": 50.0,
             "steady_recompiles": 0, "speedup_vs_k1": 2.4}]}, base)
        ms = {r["metric"]: r for r in rows
              if r["metric"].startswith("megastep")}
        assert ms["megastep[4].rounds_per_s"]["status"] == "ok"
        assert ms["megastep[pop_hier:4]"]["status"] == "skip"

    def test_baseline_without_axis_skips(self):
        from feddrift_tpu.obs.regress import compare
        rows = compare({"value": 1.0}, {"value": 1.0, "megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0}]})
        skips = [r for r in rows if r["metric"] == "megastep"]
        assert skips and skips[0]["status"] == "skip"
