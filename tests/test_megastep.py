"""Multi-iteration megastep: K fused time steps per device dispatch.

The megastep (TrainStep.train_megastep, runner.run_megastep) scans K
whole time steps — each itself an R-round fused scan with scheduled
evals — inside ONE device program, so the host touches the device once
per K iterations instead of once per iteration. The contract under test:

- bitwise parity: the K>1 path must reproduce the K=1 driver exactly
  (params, eval series, decision trajectories) — same fold_in key
  sequence, same opt-state reinit, same eval cadence;
- validity gating: ``_megastep_span`` fuses only configurations the scan
  actually models, and ``megastep_horizon`` clamps the span at the next
  drift-decision boundary;
- compile stability: one program per K, compiled once — steady-state
  blocks must hit the jit cache (the perf win evaporates otherwise);
- the regress gate's megastep axis (rounds/s floor, absolute
  zero-recompile, host-overhead-beats-K=1).
"""

import jax
import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment


def _cfg(**kw):
    base = dict(dataset="sea", model="lr", concept_drift_algo="oblivious",
                concept_drift_algo_arg="", concept_num=1,
                client_num_in_total=8, client_num_per_round=8,
                train_iterations=8, comm_round=5, epochs=1, batch_size=50,
                sample_num=50, frequency_of_the_test=5, lr=0.05,
                seed=7, trace_sync=True)
    base.update(kw)
    return ExperimentConfig(**base)


def _leafdiff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
               for x, y in zip(la, lb))


@pytest.mark.slow
class TestMegastepParity:
    """K=4 vs K=1 must be bitwise-identical end to end."""

    def _pair(self, **kw):
        return run_experiment(_cfg(megastep_k=1, **kw)), \
               run_experiment(_cfg(megastep_k=4, **kw))

    def test_oblivious_bitwise(self):
        e1, e4 = self._pair()
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        assert e1.logger.series("Train/Acc") == e4.logger.series("Train/Acc")

    def test_softcluster_cadence_bitwise(self):
        # cadence-3 softcluster: decisions at t=0,3,6 — the megastep fuses
        # the decision-free gaps and the carried-forward weight trajectory
        # must match the sequential driver exactly
        kw = dict(concept_drift_algo="softcluster",
                  concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
                  decision_cadence=3)
        e1, e4 = self._pair(**kw)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")
        assert np.array_equal(e1.algo.weights, e4.algo.weights)

    def test_partial_participation_bitwise(self):
        # per-round client masks ride through the scan as a [K, R, C] xs
        e1, e4 = self._pair(client_num_per_round=2)
        assert _leafdiff(e1.pool.params, e4.pool.params) == 0.0
        assert e1.logger.series("Test/Acc") == e4.logger.series("Test/Acc")

    def test_single_compile_across_blocks(self):
        # 8 iterations at K=4 = two blocks; block 2's params are scan
        # outputs (committed NamedSharding) — the init-time pool placement
        # must make block 1 present the same signature, or every steady
        # block silently recompiles the whole program. (_cache_size is
        # per jit-wrapped function, shared by every TrainStep via the
        # static self argnum — so assert NO GROWTH past block 1, not an
        # absolute count.)
        exp = Experiment(_cfg(megastep_k=4))
        t = exp.run_megastep(0, exp._megastep_span(0))
        n0 = exp.step._train_megastep_jit._cache_size()
        while t < exp.cfg.train_iterations:
            t += exp.run_megastep(t, exp._megastep_span(t))
        assert exp.step._train_megastep_jit._cache_size() == n0


class TestMegastepGate:
    """_megastep_span: fuse only what the scan models, clamp at decision
    boundaries and the end of the run."""

    def test_span_and_tail_clamp(self):
        exp = Experiment(_cfg(megastep_k=4))
        assert exp._megastep_span(0) == 4
        assert exp._megastep_span(6) == 2      # train_iterations=8 tail
        assert exp._megastep_span(7) == 1

    def test_k1_and_unfusable_configs_stay_sequential(self):
        assert Experiment(_cfg(megastep_k=1))._megastep_span(0) == 1
        assert Experiment(
            _cfg(megastep_k=4, chunk_rounds=False))._megastep_span(0) == 1
        # delta codec threads per-iteration carry the scan does not model
        assert Experiment(
            _cfg(megastep_k=4, compress_codec="topk"))._megastep_span(0) == 1

    def test_horizon_window_stretches_full_tail(self):
        exp = Experiment(_cfg(megastep_k=4, concept_drift_algo="win-1"))
        assert exp.algo.megastep_horizon(0) == 8
        assert exp.algo.megastep_horizon(5) == 3

    def test_horizon_softcluster_cadence(self):
        exp = Experiment(_cfg(
            megastep_k=4, concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
            decision_cadence=3))
        # step t may itself decide; the horizon reaches the NEXT boundary
        assert exp.algo.megastep_horizon(0) == 3
        assert exp.algo.megastep_horizon(1) == 2
        assert exp.algo.megastep_horizon(2) == 1
        assert exp.algo.megastep_horizon(3) == 3
        assert exp._megastep_span(0) == 3      # clamped below megastep_k
        assert exp._megastep_span(1) == 2

    def test_horizon_cadence_one_never_fuses(self):
        exp = Experiment(_cfg(
            megastep_k=4, concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3))
        assert exp.algo.megastep_horizon(2) == 1
        assert exp._megastep_span(2) == 1

    def test_horizon_conservative_default(self):
        from feddrift_tpu.algorithms.base import DriftAlgorithm
        # the base contract: algorithms that don't certify decision-free
        # stretches inherit no fusion at all
        assert DriftAlgorithm.megastep_horizon.__get__(object())(5) == 1


class TestOfferCacheAliasing:
    """offer_acc_matrix hands the SAME ndarray to every consumer; the
    frozen-array + identity-key + rebind-invalidation trio keeps one
    consumer's mutation (or a dataset swap) from corrupting the rest."""

    def test_offered_matrix_is_frozen(self):
        exp = Experiment(_cfg())
        m = np.full((exp.pool.num_models, exp.algo.C), 0.5, np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        got = exp.algo.acc_matrix_at(0)
        assert got is not m or not got.flags.writeable
        with pytest.raises(ValueError):
            got[0, 0] = 0.0

    def test_rebind_invalidates_offer(self):
        exp = Experiment(_cfg())
        m = np.full((exp.pool.num_models, exp.algo.C), 0.5, np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        exp.algo.rebind_data(exp.x, exp.y)
        assert exp.algo._acc_offer is None

    def test_pool_mutation_misses_cache(self):
        exp = Experiment(_cfg())
        m = np.zeros((exp.pool.num_models, exp.algo.C), np.float32)
        exp.algo.offer_acc_matrix(exp.pool.params, {0: m})
        # any writeback rebinds pool.params to a new object: identity key
        exp.pool.params = jax.tree_util.tree_map(lambda l: l + 0,
                                                 exp.pool.params)
        fresh = exp.algo.acc_matrix_at(0)
        assert fresh is not m and float(fresh.max()) > 0.0


class TestMegastepRegressAxis:
    def test_floor_zero_recompile_and_host_overhead_gates(self):
        from feddrift_tpu.obs.regress import compare
        base = {"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.8},
            {"megastep_k": 4, "rounds_per_sec": 160.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.3}]}
        ok = compare({"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 95.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.82},
            {"megastep_k": 4, "rounds_per_sec": 150.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.35}]}, base)
        ms = {r["metric"]: r for r in ok
              if r["metric"].startswith("megastep")}
        assert ms["megastep[4].rounds_per_s"]["status"] == "ok"
        assert ms["megastep[4].steady_recompiles"]["status"] == "ok"
        assert ms["megastep[4].host_overhead_frac"]["status"] == "ok"
        bad = compare({"megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0,
             "steady_recompiles": 0, "host_overhead_frac": 0.5},
            {"megastep_k": 4, "rounds_per_sec": 50.0,
             "steady_recompiles": 1, "host_overhead_frac": 0.6}]}, base)
        ms = {r["metric"]: r for r in bad
              if r["metric"].startswith("megastep")}
        assert ms["megastep[4].rounds_per_s"]["status"] == "regress"
        # absolute gates: any recompile, or K>1 overhead >= this run's K=1
        assert ms["megastep[4].steady_recompiles"]["status"] == "regress"
        assert ms["megastep[4].host_overhead_frac"]["status"] == "regress"

    def test_baseline_without_axis_skips(self):
        from feddrift_tpu.obs.regress import compare
        rows = compare({"value": 1.0}, {"value": 1.0, "megastep": [
            {"megastep_k": 1, "rounds_per_sec": 100.0}]})
        skips = [r for r in rows if r["metric"] == "megastep"]
        assert skips and skips[0]["status"] == "skip"
