"""End-to-end precision policy (core/precision.py): preset resolution,
pool/opt-state storage dtype, the agg-in-f32 aggregation boundary, wire
frames at the policy dtype, and the serving plane's dtype preservation.

The load-bearing contracts pinned here:

- the ``f32`` policy is BITWISE identical to the historical default
  ("auto" off-TPU) — every cast site is a same-dtype identity, so
  enabling the policy machinery costs nothing on existing runs;
- one policy, three drivers: per-round host loop, fused single-iteration
  scan and the K>1 megastep must agree bitwise under bf16 too — the
  policy threads through all three compiled paths, not just one;
- robust aggregation is structural: trimmed-mean/krum active/rejected
  counts are identical across policies (the f32 aggregation master keeps
  sort order; the trim count is a function of participation, not values);
- zero steady-state recompiles per policy after warmup — a policy is ONE
  jit signature, not a per-round dtype lottery;
- wire frames declare and honor their dtype: bf16 halves the "none"
  payload, decoders reject undeclared widths instead of misparsing.
"""

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.comm.compress import (CorruptFrameError, UpdateReceiver,
                                        UpdateSender, decode_frame,
                                        encode_frame, simulate_codec)
from feddrift_tpu.comm.pubsub import Broker
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.core.precision import (PRESETS, PrecisionPolicy,
                                         cast_floating, match_dtypes,
                                         resolve_precision)
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model
from feddrift_tpu.platform.serving import ServingState
from feddrift_tpu.simulation.runner import Experiment, run_experiment

BF16 = np.dtype(ml_dtypes.bfloat16)


def _cfg(**kw):
    base = dict(dataset="sea", model="lr", concept_drift_algo="oblivious",
                concept_drift_algo_arg="", concept_num=1,
                client_num_in_total=8, client_num_per_round=8,
                train_iterations=6, comm_round=3, epochs=1, batch_size=50,
                sample_num=50, frequency_of_the_test=3, lr=0.05,
                seed=7, trace_sync=True)
    base.update(kw)
    return ExperimentConfig(**base)


def _leafdiff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x, np.float32)
                                   - np.asarray(y, np.float32))))
               for x, y in zip(la, lb))


def _float_dtypes(tree):
    return {str(l.dtype) for l in jax.tree_util.tree_leaves(tree)
            if jnp.issubdtype(l.dtype, jnp.floating)}


# ---------------------------------------------------------------- policy
class TestPolicyResolution:
    def test_presets(self):
        f32 = PRESETS["f32"]
        assert f32.is_f32
        assert (f32.param_dtype, f32.compute_dtype, f32.agg_dtype,
                f32.eval_dtype, f32.wire_dtype) == ("float32",) * 5
        mixed = PRESETS["bf16_mixed"]
        assert (mixed.param_dtype, mixed.compute_dtype,
                mixed.wire_dtype) == ("bfloat16",) * 3
        # the guide rule: accumulate in f32, store in bf16
        assert mixed.agg_dtype == "float32"
        assert mixed.eval_dtype == "float32"
        pure = PRESETS["bf16_pure"]
        assert (pure.param_dtype, pure.compute_dtype, pure.agg_dtype,
                pure.eval_dtype, pure.wire_dtype) == ("bfloat16",) * 5

    def test_auto_off_tpu_is_f32(self):
        pol = resolve_precision(_cfg(), backend="cpu")
        assert pol.is_f32 and pol.param_dtype == "float32"

    def test_auto_on_tpu_keeps_bf16_apply_boundary(self):
        pol = resolve_precision(_cfg(compute_dtype="bfloat16"),
                                backend="tpu")
        assert pol.compute_dtype == "bfloat16"
        assert pol.param_dtype == "float32"

    def test_explicit_preset_ignores_backend(self):
        for backend in ("cpu", "tpu", None):
            pol = resolve_precision(_cfg(precision="bf16_mixed"),
                                    backend=backend)
            assert pol is PRESETS["bf16_mixed"]

    def test_unknown_precision_rejected(self):
        with pytest.raises(ValueError, match="precision"):
            _cfg(precision="fp8")
        with pytest.raises(ValueError):
            PrecisionPolicy(param_dtype="float16")

    def test_cast_floating_skips_ints_and_same_dtype_identity(self):
        tree = {"w": jnp.ones((2, 2), jnp.float32),
                "n": jnp.ones((2,), jnp.int32)}
        out = cast_floating(tree, "bfloat16")
        assert str(out["w"].dtype) == "bfloat16"
        assert out["n"] is tree["n"]          # ints untouched
        same = cast_floating(tree, "float32")
        assert same["w"] is tree["w"]         # identity, no new op

    def test_match_dtypes_follows_reference_leaves(self):
        tree = {"a": jnp.ones((3,), jnp.float32),
                "b": jnp.ones((3,), jnp.float32)}
        like = {"a": jnp.ones((5,), jnp.bfloat16),   # shapes may differ
                "b": jnp.ones((5,), jnp.float32)}
        out = match_dtypes(tree, like)
        assert str(out["a"].dtype) == "bfloat16"
        assert out["b"] is tree["b"]


# ---------------------------------------------------------------- pool
class TestPoolParamDtype:
    def _pool(self, **kw):
        cfg = ExperimentConfig(dataset="sea", train_iterations=2,
                               sample_num=16)
        ds = make_dataset(cfg)
        mod = create_model("fnn", ds, cfg)
        return ModelPool.create(mod, jnp.zeros((2, 3)), 3, seed=7, **kw)

    def test_pool_stored_at_param_dtype(self):
        pool = self._pool(param_dtype="bfloat16")
        assert _float_dtypes(pool.params) == {"bfloat16"}

    def test_reinit_slot_preserves_dtype(self):
        pool = self._pool(param_dtype="bfloat16", identical=True)
        pool.reinit_slot(1)
        assert _float_dtypes(pool.params) == {"bfloat16"}

    def test_distinct_reinit_slot_preserves_dtype(self):
        pool = self._pool(param_dtype="bfloat16")
        pool.distinct_reinit_slot(2, seed=123)
        assert _float_dtypes(pool.params) == {"bfloat16"}


# ---------------------------------------------------------------- e2e
class TestPolicyParity:
    def test_f32_policy_bitwise_backcompat(self):
        # enabling the policy machinery must not perturb a single bit of
        # the historical default path
        e_auto = run_experiment(_cfg())               # precision="auto"
        e_f32 = run_experiment(_cfg(precision="f32"))
        assert _leafdiff(e_auto.pool.params, e_f32.pool.params) == 0.0
        assert e_auto.logger.series("Test/Acc") == \
            e_f32.logger.series("Test/Acc")
        assert _float_dtypes(e_f32.pool.params) == {"float32"}

    def test_bf16_mixed_accuracy_within_tolerance(self):
        e_f32 = run_experiment(_cfg(precision="f32"))
        e_mix = run_experiment(_cfg(precision="bf16_mixed"))
        assert _float_dtypes(e_mix.pool.params) == {"bfloat16"}
        a32 = e_f32.logger.last("Test/Acc")
        a16 = e_mix.logger.last("Test/Acc")
        assert abs(a32 - a16) <= 0.1, (a32, a16)

    def test_bf16_pure_trains(self):
        e = run_experiment(_cfg(precision="bf16_pure"))
        assert _float_dtypes(e.pool.params) == {"bfloat16"}
        assert e.logger.last("Test/Acc") > 0.6

    def test_opt_state_follows_param_dtype(self):
        # optimizer moments are the dominant resident [M, C, ...] buffers:
        # they must inherit the bf16 storage, not silently stay f32
        exp = Experiment(_cfg(precision="bf16_mixed"))
        opt = exp.step.init_opt_states(
            exp.pool.params, exp.pool.num_models, exp.C_pad)
        assert _float_dtypes(opt) <= {"bfloat16"}

    def test_three_drivers_bitwise_under_bf16(self):
        # one policy, three compiled paths: the per-round host loop, the
        # fused single-iteration scan and the K=4 megastep must produce
        # the SAME bf16 pool — the policy is threaded, not re-derived
        kw = dict(precision="bf16_mixed", train_iterations=8)
        e_round = run_experiment(_cfg(chunk_rounds=False, **kw))
        e_fused = run_experiment(_cfg(megastep_k=1, **kw))
        e_mega = run_experiment(_cfg(megastep_k=4, **kw))
        assert "train_megastep" in e_mega.step._signatures
        assert _leafdiff(e_round.pool.params, e_fused.pool.params) == 0.0
        assert _leafdiff(e_fused.pool.params, e_mega.pool.params) == 0.0
        assert e_round.logger.series("Test/Acc") == \
            e_mega.logger.series("Test/Acc")

    def test_robust_agg_counts_identical_across_policies(self):
        # trimmed-mean trims a FIXED per-coordinate count: the defense's
        # active/rejected bookkeeping is participation-structural, so a
        # precision change must not alter a single count
        kw = dict(byzantine_clients="0,3", robust_agg="trimmed_mean",
                  robust_trim_frac=0.3)

        def stats(exp):
            return [(e["strategy"], e["active"], e["rejected"], e["clipped"])
                    for e in exp.events.ring
                    if e["kind"] == "robust_agg_applied"]

        e_f32 = run_experiment(_cfg(precision="f32", **kw))
        e_mix = run_experiment(_cfg(precision="bf16_mixed", **kw))
        s32, s16 = stats(e_f32), stats(e_mix)
        assert s32 and s32 == s16
        assert any(r[2] > 0 for r in s32)     # non-vacuous: trims happened

    def test_zero_recompiles_after_warmup_per_policy(self):
        # 8 iterations at K=4 = two blocks; block 2 must replay block 1's
        # signature under bf16 exactly as it does under f32
        for precision in ("f32", "bf16_mixed"):
            exp = Experiment(_cfg(precision=precision, megastep_k=4,
                                  train_iterations=8))
            t = exp.run_megastep(0, exp._megastep_span(0))
            n0 = exp.step._train_megastep_jit._cache_size()
            sigs0 = len(exp.step._signatures["train_megastep"])
            assert sigs0 == 1
            while t < exp.cfg.train_iterations:
                t += exp.run_megastep(t, exp._megastep_span(t))
            assert exp.step._train_megastep_jit._cache_size() == n0
            assert len(exp.step._signatures["train_megastep"]) == 1

    def test_run_start_event_names_policy(self):
        exp = Experiment(_cfg(precision="bf16_mixed"))
        starts = [e for e in exp.events.ring if e["kind"] == "run_start"]
        assert starts and starts[-1]["precision"] == "bf16_mixed"
        assert starts[-1]["param_dtype"] == "bfloat16"


# ---------------------------------------------------------------- wire
RNG = np.random.RandomState(0)
ARR32 = RNG.randn(40, 37).astype(np.float32)
ARR16 = ARR32.astype(BF16)


class TestWireDtype:
    def test_frames_declare_actual_dtype(self):
        assert encode_frame(ARR32, "none")["dtype"] == "float32"
        assert encode_frame(ARR16, "none")["dtype"] == "bfloat16"

    def test_bf16_none_roundtrip_halves_payload(self):
        import base64
        f32, f16 = encode_frame(ARR32, "none"), encode_frame(ARR16, "none")
        raw32 = len(base64.b64decode(f32["p"]["data"]))
        raw16 = len(base64.b64decode(f16["p"]["data"]))
        assert raw16 * 2 == raw32
        out = decode_frame(f16)
        assert out.dtype == BF16 and (out == ARR16).all()

    def test_bf16_int8_roundtrip(self):
        out = decode_frame(encode_frame(ARR16, "int8"))
        assert out.dtype == BF16
        a = ARR16.astype(np.float32)
        step = (a.max() - a.min()) / 255.0
        assert np.abs(out.astype(np.float32) - a).max() <= step / 2 + 0.01

    def test_bf16_delta_chain_carries_dtype(self):
        prev = None
        for _ in range(4):
            arr = RNG.randn(30, 11).astype(np.float32).astype(BF16)
            out = decode_frame(encode_frame(arr, "delta", prev=prev),
                               prev=prev)
            assert out.dtype == BF16
            assert np.abs(out.astype(np.float32)
                          - arr.astype(np.float32)).max() < 0.1
            prev = out

    def test_undeclared_dtype_rejected(self):
        from feddrift_tpu.comm.compress import _digest
        frame = encode_frame(ARR32.astype(np.float64), "none")
        assert frame["dtype"] == "float32"    # normalized at encode
        frame = encode_frame(ARR32, "none")
        # an unmodified forgery dies on the digest; re-sign it so the
        # decoder's own dtype whitelist is what rejects it
        frame["dtype"] = "float64"
        frame["digest"] = _digest(frame)
        with pytest.raises(CorruptFrameError, match="dtype"):
            decode_frame(frame)

    def test_width_mismatch_rejected(self):
        from feddrift_tpu.comm.compress import _digest
        # a frame that declares f32 but carries a bf16-width payload must
        # fail the length check, not silently misparse
        frame = encode_frame(ARR16, "none")
        frame["dtype"] = "float32"
        frame["digest"] = _digest(frame)
        with pytest.raises(CorruptFrameError, match="length"):
            decode_frame(frame)

    def test_sender_wire_bytes_halve_for_bf16(self):
        # the raw-bytes baseline is the ACTUAL dtype's width: a bf16 link
        # reports half the f32 link's bytes instead of pretending every
        # update is 4 bytes/element
        obs.configure(None)
        broker = Broker()
        tx = UpdateSender(broker, "fl/u", codec="int8")
        rx = UpdateReceiver(broker, "fl/u")
        tx.send("u32", ARR32)
        tx.send("u16", ARR16)
        _, got32 = rx.recv(timeout=1.0)
        _, got16 = rx.recv(timeout=1.0)
        assert got32.dtype == np.float32 and got16.dtype == BF16
        evs = obs.get_bus().events("update_compressed")
        by_name = {e["update"]: e for e in evs
                   if e["update"] in ("u32", "u16")}
        # raw_bytes is the would-be uncompressed frame at the ACTUAL
        # dtype; base64 payload halves, headers add a fixed tail
        assert by_name["u16"]["raw_bytes"] < 0.55 * by_name["u32"]["raw_bytes"]

    def test_simulate_codec_preserves_stack_dtype(self):
        # device-side codec simulation mirrors the wire contract: quantize
        # in f32 arithmetic, return the stack's own dtype (int8-from-bf16
        # without a silent upcast of the [M, C, ...] update stack)
        stack32 = jnp.asarray(RNG.randn(2, 3, 16)).astype(jnp.float32)
        out32, _ = simulate_codec((stack32,), "int8")
        assert out32[0].dtype == jnp.float32
        stack16 = stack32.astype(jnp.bfloat16)
        for codec in ("int8", "topk"):
            out16, _ = simulate_codec((stack16,), codec, topk_frac=0.25)
            assert out16[0].dtype == jnp.bfloat16


# ---------------------------------------------------------------- serving
class TestServingDtype:
    def test_pool_dtype_preserved_end_to_end(self):
        init = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)
                .astype(BF16), "b": np.zeros(3, np.float32)}
        state = ServingState(init)
        assert state.params["w"].dtype == BF16
        d0, d1 = state.register(), state.register()
        up = {k: np.asarray(v, np.float32).tolist()
              for k, v in init.items()}
        state.upload(d0, 10.0, up)
        r = state.upload(d1, 30.0, up)
        assert r == 1
        # aggregation ran through the f32 master and committed back at
        # the POOL dtype — no silent upcast
        assert state.params["w"].dtype == BF16
        assert state.params["b"].dtype == np.float32

    def test_json_decode_boundary_still_f32_for_f32_pool(self):
        state = ServingState({"w": np.zeros((2, 2), np.float32)})
        state.register()
        state.upload(0, 1.0, {"w": [[1.0, 2.0], [3.0, 4.0]]})
        assert state.params["w"].dtype == np.float32


# ---------------------------------------------------------------- norm
class TestHalfWidthNorm:
    """models/resnet.py _Norm: the bf16 branch must stay half-width.

    jnp reductions upcast bf16 inputs by materialising a full-size f32
    copy of the feature map; the norm's half-width branch accumulates the
    moments through an f32-preferring dot instead. The gate is on the
    LOWERED HLO: no full-size f32 tensor may appear in a bf16 norm."""

    def _norm(self):
        from feddrift_tpu.models.resnet import _Norm
        return _Norm("batch")

    def test_bf16_norm_close_to_f32(self):
        rng = np.random.RandomState(0)
        x32 = jnp.asarray(rng.normal(2.0, 3.0, (8, 8, 8, 16))
                          .astype(np.float32))
        norm = self._norm()
        params = norm.init(jax.random.PRNGKey(0), x32)
        y32 = norm.apply(params, x32)
        p16 = jax.tree_util.tree_map(
            lambda l: l.astype(jnp.bfloat16), params)
        y16 = norm.apply(p16, x32.astype(jnp.bfloat16))
        assert y16.dtype == jnp.bfloat16
        # normalised output is ~unit-scale; bf16 carries ~2-3 decimal
        # digits, and the E[x^2]-E[x]^2 moments ride an f32 accumulator
        diff = np.max(np.abs(np.asarray(y16, dtype=np.float32)
                             - np.asarray(y32)))
        assert diff < 0.1, diff

    def test_bf16_norm_lowers_without_f32_feature_map(self):
        norm = self._norm()
        x16 = jnp.zeros((8, 8, 8, 16), jnp.bfloat16)
        p16 = jax.tree_util.tree_map(
            lambda l: l.astype(jnp.bfloat16),
            norm.init(jax.random.PRNGKey(0), x16))
        txt = jax.jit(norm.apply).lower(p16, x16).as_text()
        assert "tensor<8x8x8x16xf32>" not in txt
        assert "tensor<4096x16xf32>" not in txt      # reshaped view

    def test_f32_norm_path_unchanged(self):
        # the f32 branch is the pre-policy program: mean/var directly
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 8)).astype(np.float32))
        norm = self._norm()
        params = norm.init(jax.random.PRNGKey(0), x)
        y = norm.apply(params, x)
        mean = np.asarray(x).mean(axis=(0, 1, 2), keepdims=True)
        var = np.asarray(x).var(axis=(0, 1, 2), keepdims=True)
        ref = (np.asarray(x) - mean) / np.sqrt(var + 1e-5)
        assert y.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-5,
                                   atol=2e-5)


# ---------------------------------------------------------------- regress
class TestPrecisionRegressAxis:
    def _rows(self, policy, rps, acc, rec=0, br=None, wr=None):
        e = {"variant": "resnet", "policy": policy, "rounds_per_sec": rps,
             "final_test_acc": acc, "steady_recompiles": rec}
        if br is not None:
            e["bytes_accessed_ratio"] = br
        if wr is not None:
            e["wire_bytes_ratio"] = wr
        return e

    def _artifact(self, rps16=8.0, acc16=0.70, rec=0, br=0.5, wr=0.5):
        return {"precision": [
            self._rows("f32", 6.0, 0.72),
            self._rows("bf16_mixed", rps16, acc16, rec, br, wr)]}

    def test_ok_and_absolute_ceiling_gates(self):
        from feddrift_tpu.obs.regress import compare
        base = self._artifact()
        ok = compare(self._artifact(rps16=7.8, acc16=0.69), base)
        ms = {r["metric"]: r for r in ok
              if r["metric"].startswith("precision")}
        assert ms["precision[resnet:bf16_mixed].rounds_per_s"][
            "status"] == "ok"
        assert ms["precision[resnet:bf16_mixed].final_test_acc"][
            "status"] == "ok"
        assert ms["precision[resnet:bf16_mixed].bytes_accessed_ratio"][
            "status"] == "ok"
        assert ms["precision[resnet:bf16_mixed].wire_bytes_ratio"][
            "status"] == "ok"
        # the ratio/recompile/accuracy gates are ABSOLUTE: a baseline
        # that itself regressed cannot grandfather a bad candidate in
        bad = compare(self._artifact(acc16=0.60, rec=1, br=0.8, wr=0.7),
                      self._artifact(acc16=0.60, rec=1, br=0.8, wr=0.7))
        ms = {r["metric"]: r for r in bad
              if r["metric"].startswith("precision")}
        assert ms["precision[resnet:bf16_mixed].final_test_acc"][
            "status"] == "regress"      # 0.60 < own f32 0.72 - 0.05
        assert ms["precision[resnet:bf16_mixed].steady_recompiles"][
            "status"] == "regress"
        assert ms["precision[resnet:bf16_mixed].bytes_accessed_ratio"][
            "status"] == "regress"      # 0.8 > 0.60 ceiling
        assert ms["precision[resnet:bf16_mixed].wire_bytes_ratio"][
            "status"] == "regress"      # 0.7 > 0.55 ceiling

    def test_acc_gate_is_vs_own_f32_row_and_f32_row_exempt(self):
        from feddrift_tpu.obs.regress import compare
        rows = compare(self._artifact(), self._artifact())
        named = [r["metric"] for r in rows
                 if r["metric"].startswith("precision")]
        # the f32 row carries no precision-acc gate (it IS the reference)
        assert "precision[resnet:f32].final_test_acc" not in named
        assert "precision[resnet:bf16_mixed].final_test_acc" in named

    def test_missing_variant_point_skips(self):
        from feddrift_tpu.obs.regress import compare
        base = {"precision": [self._rows("f32", 6.0, 0.72)]}
        rows = compare(self._artifact(), base)
        ms = {r["metric"]: r for r in rows
              if r["metric"].startswith("precision")}
        assert ms["precision[resnet:bf16_mixed]"]["status"] == "skip"

    def test_baseline_without_axis_skips(self):
        from feddrift_tpu.obs.regress import compare
        rows = compare({"value": 1.0}, self._artifact())
        skips = [r for r in rows if r["metric"] == "precision"]
        assert skips and skips[0]["status"] == "skip"
