"""Live ops plane tests (obs/live.py + obs/quantiles.py): P² streaming
quantile accuracy, /metrics + /healthz + /status endpoints, the SLO
burn-rate engine, the fleet snapshot merge over a real TCP broker, and
`report --follow` rotation folding. Pure host logic except the runner
end-to-end (slow tier: compiles a train_round)."""

from __future__ import annotations

import io
import json
import os
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from feddrift_tpu import obs
from feddrift_tpu.obs import live
from feddrift_tpu.obs.events import EventBus
from feddrift_tpu.obs.instruments import DEFAULT_BUCKETS, Registry
from feddrift_tpu.obs.quantiles import P2Estimator, QuantileSketch


def _get(url: str, timeout: float = 5.0):
    """Bounded GET returning (status, body) — 503s carry a body too."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestP2Estimator:
    def test_exact_below_marker_window(self):
        """Under 5 samples the estimator is exact nearest-rank, not an
        interpolation artifact."""
        est = P2Estimator(0.5)
        for v in (3.0, 1.0, 2.0):
            est.observe(v)
        assert est.quantile() == 2.0
        assert P2Estimator(0.99).quantile() is None

    @pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
    def test_accuracy_uniform(self, q):
        rng = random.Random(7)
        xs = [rng.random() for _ in range(20000)]
        est = P2Estimator(q)
        for x in xs:
            est.observe(x)
        exact = sorted(xs)[int(q * len(xs)) - 1]
        assert abs(est.quantile() - exact) < 0.01, (q, est.quantile(), exact)

    @pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
    def test_accuracy_heavy_tail(self, q):
        """Exponential tail — the shape round walls actually have."""
        rng = random.Random(11)
        xs = [rng.expovariate(1.0) for _ in range(20000)]
        est = P2Estimator(q)
        for x in xs:
            est.observe(x)
        exact = sorted(xs)[int(q * len(xs)) - 1]
        assert abs(est.quantile() - exact) / exact < 0.1, \
            (q, est.quantile(), exact)

    def test_sketch_snapshot_and_thread_safety(self):
        sk = QuantileSketch()
        threads = [threading.Thread(
            target=lambda: [sk.observe(0.5) for _ in range(500)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = sk.snapshot()
        assert snap["count"] == 2000
        assert abs(snap["sum"] - 1000.0) < 1e-6
        assert snap["min"] == snap["max"] == 0.5
        assert set(snap["quantiles"]) == {"0.5", "0.95", "0.99"}
        assert abs(snap["quantiles"]["0.99"] - 0.5) < 1e-9

    def test_sketch_p99_agrees_with_histogram_bucket(self):
        """The acceptance cross-check: the live sketch p99 must land
        inside the post-hoc histogram's p99 bucket (one bucket width)."""
        from feddrift_tpu.obs.instruments import Histogram
        rng = random.Random(3)
        hist = Histogram(DEFAULT_BUCKETS)
        sk = QuantileSketch()
        for _ in range(5000):
            v = min(abs(rng.lognormvariate(-2.0, 1.0)), 90.0)
            hist.observe(v)
            sk.observe(v)
        snap = hist.snapshot()
        # histogram p99: first bucket whose cumulative count crosses 99%
        bounds = list(hist.bounds) + [float("inf")]
        cum, lo = 0, 0.0
        for i, b in enumerate(bounds):
            cum += hist.bucket_counts[i]
            if cum >= 0.99 * snap["count"]:
                hi = b
                break
            lo = b
        p99 = sk.snapshot()["quantiles"]["0.99"]
        assert lo <= p99 <= hi, f"sketch p99 {p99} outside bucket ({lo}, {hi}]"


class TestStatusBoardAndTap:
    def test_board_beat_age_and_fields(self):
        board = live.StatusBoard()
        assert board.last_iteration_age() is None
        board.beat(iteration=4)
        board.update(rounds_per_s=2.5)
        assert board.fields()["iteration"] == 4
        assert board.fields()["rounds_per_s"] == 2.5
        assert 0.0 <= board.last_iteration_age() < 5.0
        board.reset()
        assert board.fields() == {} and board.last_iteration_age() is None

    def test_tap_feeds_board_from_events(self):
        board = live.StatusBoard()
        tap = live.StatusTap(board)
        bus = EventBus(None)
        tap.attach(bus)
        bus.emit("run_start", num_models=1)
        bus.emit("iteration_end", iteration=2, rounds_per_s=3.0,
                 test_acc=0.8, wall_s=1.5)
        bus.emit("cluster_state", num_models=4)
        bus.emit("cluster_assign", oracle_ari=0.9)
        f = board.fields()
        assert f["iteration"] == 2 and f["rounds_per_s"] == 3.0
        assert f["num_models"] == 4 and f["oracle_ari"] == 0.9
        assert f["run_phase"] == "running"
        bus.emit("run_end", test_acc=0.8)
        assert board.fields()["run_phase"] == "done"


class TestSLOEngine:
    def _floor(self, **kw):
        base = dict(name="rps_floor", kinds=("iteration_end",),
                    value=lambda r: r.get("rounds_per_s"), objective=1.0,
                    direction="min", window=4, budget_frac=0.25,
                    burn_rate=2.0, min_samples=3, cooldown_s=10.0)
        base.update(kw)
        return live.SLObjective(**base)

    def test_fires_on_sustained_violation(self, tmp_path):
        clock = [100.0]
        apath = str(tmp_path / "alerts.jsonl")
        eng = live.SLOEngine([self._floor()], path=apath,
                             time_fn=lambda: clock[0])
        for _ in range(3):
            eng.observe({"kind": "iteration_end", "rounds_per_s": 0.1})
        assert len(eng.burns) == 1
        assert eng.burns[0]["slo"] == "rps_floor"
        assert eng.burns[0]["rule"] == "slo:rps_floor"
        assert [a["slo"] for a in eng.active()] == ["rps_floor"]
        (rec,) = [json.loads(l) for l in open(apath)]
        assert rec["kind"] == "slo_burn" and rec["burn_frac"] == 1.0

    def test_stays_quiet_within_budget(self):
        eng = live.SLOEngine([self._floor()], time_fn=lambda: 0.0)
        # at most 1 violation per 4-sample window (burn needs 2): quiet
        for v in (0.1, 2.0, 2.0, 2.0, 2.0, 0.1, 2.0, 2.0, 2.0):
            eng.observe({"kind": "iteration_end", "rounds_per_s": v})
        assert eng.burns == [] and eng.active() == []
        # and below min_samples nothing fires even at 100% violation
        eng2 = live.SLOEngine([self._floor()], time_fn=lambda: 0.0)
        eng2.observe({"kind": "iteration_end", "rounds_per_s": 0.1})
        eng2.observe({"kind": "iteration_end", "rounds_per_s": 0.1})
        assert eng2.burns == []

    def test_cooldown_and_recovery(self):
        clock = [0.0]
        eng = live.SLOEngine([self._floor()], time_fn=lambda: clock[0])
        for _ in range(4):
            eng.observe({"kind": "iteration_end", "rounds_per_s": 0.1})
        assert len(eng.burns) == 1            # cooldown holds repeats back
        assert eng.active()                   # ...but it stays active
        clock[0] = 20.0                       # past cooldown, still burning
        eng.observe({"kind": "iteration_end", "rounds_per_s": 0.1})
        assert len(eng.burns) == 2
        # recovery: healthy samples flush the window -> active clears
        for _ in range(4):
            eng.observe({"kind": "iteration_end", "rounds_per_s": 5.0})
        assert eng.active() == []

    def test_incident_mode_broker_liveness(self):
        eng = live.SLOEngine(live.default_slos(), time_fn=lambda: 0.0)
        eng.observe({"kind": "heartbeat_missed", "transport": "netbroker"})
        assert [b["slo"] for b in eng.burns] == ["broker_liveness"]
        assert eng.burns[0]["severity"] == "crit"
        # one healthy sample (the reconnect) heals incident mode
        eng.observe({"kind": "conn_reconnect", "transport": "netbroker"})
        assert eng.active() == []

    def test_emits_slo_burn_on_attached_bus(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        bus = EventBus(path)
        eng = live.SLOEngine([self._floor()]).attach(bus)
        for _ in range(3):
            bus.emit("iteration_end", rounds_per_s=0.1)
        bus.close()
        assert eng.burns and eng.burns[0]["kind"] == "slo_burn"
        kinds = [json.loads(l)["kind"] for l in open(path)]
        assert kinds.count("slo_burn") == 1

    def test_default_slos_gating(self):
        names = {o.name for o in live.default_slos()}
        assert names == {"broker_liveness"}
        names = {o.name for o in live.default_slos(
            rounds_per_s=1.0, host_overhead=0.5, p99_round_wall_s=2.0,
            eval_gap=0.1)}
        assert names == {"broker_liveness", "rounds_per_s_floor",
                         "host_overhead_ceiling", "p99_round_wall",
                         "eval_gap"}


class TestOpsServer:
    def test_endpoints(self):
        reg = Registry()
        reg.counter("client_bytes_out", transport="netbroker").inc(42)
        reg.quantile_sketch("round_wall_seconds_q").observe(0.25)
        board = live.StatusBoard()
        board.beat(iteration=1)
        board.update(rounds_per_s=4.0)
        srv = live.OpsServer(port=0, reg=reg, board=board).start()
        try:
            code, body = _get(srv.url + "/metrics")
            assert code == 200
            assert b'client_bytes_out{transport="netbroker"} 42.0' in body
            assert b'round_wall_seconds_q{quantile="0.99"}' in body
            code, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert code == 200 and doc["status"] == "ok"
            assert doc["last_iteration_age_s"] is not None
            code, body = _get(srv.url + "/status")
            doc = json.loads(body)
            assert code == 200 and doc["rounds_per_s"] == 4.0
            assert "0.99" in doc["quantiles"]["round_wall_seconds_q"]
            code, _ = _get(srv.url + "/nope")
            assert code == 404
        finally:
            srv.close()

    def test_healthz_degrades_on_stall_and_crit_burn(self):
        board = live.StatusBoard()
        board.beat(iteration=0)
        eng = live.SLOEngine(live.default_slos(), time_fn=lambda: 0.0)
        srv = live.OpsServer(port=0, reg=Registry(), slo=eng, board=board,
                             stall_after_s=0.05).start()
        try:
            time.sleep(0.1)                    # beat goes stale
            code, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert code == 503 and "stalled" in doc["degraded"]
            eng.observe({"kind": "heartbeat_missed"})   # crit burn
            code, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert code == 503 and "slo_burn" in doc["degraded"]
            board.beat()                       # fresh beat clears the stall
            eng.observe({"kind": "conn_reconnect"})
            code, body = _get(srv.url + "/healthz")
            assert code == 200
        finally:
            srv.close()

    def test_healthz_aggregates_broker_clients(self):
        class FakeClient:
            _closed = False
            healthy = True
            def health(self):
                return {"healthy": self.healthy, "reconnects": 2}
        fake = FakeClient()
        live.register_broker_client(fake)
        srv = live.OpsServer(port=0, reg=Registry(),
                             board=live.StatusBoard()).start()
        try:
            code, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert code == 200 and doc["broker"]["clients"] == 1
            assert doc["broker"]["reconnects"] == 2
            fake.healthy = False
            code, body = _get(srv.url + "/healthz")
            doc = json.loads(body)
            assert code == 503 and "broker" in doc["degraded"]
        finally:
            srv.close()
            del fake                           # drop out of the WeakSet


class TestFleetPlane:
    def test_three_lane_merge_and_render(self):
        """Three processes' worth of lanes (runner, edge/0, server) over
        one real TCP broker: the collector discovers every lane via the
        announce topic and render_fleet shows one row per lane."""
        from feddrift_tpu.comm.netbroker import (NetworkBroker,
                                                 NetworkBrokerClient)
        from feddrift_tpu.platform.hierarchical import EdgeRelay

        broker = NetworkBroker()
        clients, pubs = [], []
        try:
            collector_client = NetworkBrokerClient(broker.host, broker.port)
            clients.append(collector_client)
            coll = live.FleetCollector(collector_client, namespace="t")

            relay = EdgeRelay(None, None, edge_id=0)
            relay.rounds_relayed, relay.last_members = 5, 3
            assert relay.lane == "edge/0"
            lanes = ["runner", relay.lane, "server"]
            for i, lane in enumerate(lanes):
                reg = Registry()
                reg.counter("client_bytes_out",
                            transport="netbroker").inc(100 * (i + 1))
                reg.quantile_sketch("round_wall_seconds_q").observe(0.2)
                board = live.StatusBoard()
                board.beat(iteration=i)
                board.update(rounds_per_s=float(i + 1))
                c = NetworkBrokerClient(broker.host, broker.port)
                clients.append(c)
                pub = live.OpsPublisher(
                    c, lane, namespace="t", interval_s=0.1, reg=reg,
                    board=board,
                    extra_fn=(relay.ops_snapshot_fields
                              if lane == relay.lane else None))
                pubs.append(pub.start())
            merged = coll.collect(duration_s=15.0, poll_s=0.05, min_lanes=3)
            assert set(merged) == set(lanes)
            edge = merged["edge/0"]
            assert edge["extra"] == {"edge": 0, "rounds_relayed": 5,
                                     "last_members": 3}
            assert edge["seq"] >= 1
            assert edge["health"]["status"] == "ok"
            table = live.render_fleet(merged)
            lines = table.splitlines()
            assert lines[0].split()[:2] == ["LANE", "PID"]
            assert len(lines) == 1 + 3
            assert any(l.startswith("edge/0") for l in lines[1:])
            # per-lane bytes made it through the metric filter
            assert "300" in [l for l in lines if l.startswith("server")][0]
        finally:
            for p in pubs:
                p.close()
            for c in clients:
                c.close()
            broker.close()

    def test_seq_keeps_latest_snapshot(self):
        """The merge is seq-ordered: a late-arriving stale snapshot never
        replaces a newer one."""
        class LoopClient:
            def __init__(self):
                import queue as _q
                self.qs = {}
            def subscribe(self, topic, sink=None):
                import queue as _q
                q = sink if sink is not None else _q.Queue()
                self.qs.setdefault(topic, []).append(q)
                return q
            def publish(self, topic, payload):
                for q in self.qs.get(topic, []):
                    q.put(payload)
        c = LoopClient()
        coll = live.FleetCollector(c, namespace="t")
        c.publish(live.announce_topic("t"), json.dumps({"lane": "a"}))
        coll.poll()
        c.publish(live.ops_topic("t", "a"),
                  json.dumps({"lane": "a", "seq": 5, "pid": 1}))
        c.publish(live.ops_topic("t", "a"),
                  json.dumps({"lane": "a", "seq": 3, "pid": 0}))
        lanes = coll.poll()
        assert lanes["a"]["seq"] == 5

    def test_emit_snapshot_records_event(self, tmp_path):
        old = obs.get_bus()
        try:
            bus = obs.configure(str(tmp_path / "events.jsonl"))
            board = live.StatusBoard()
            board.update(rounds_per_s=2.0)
            rec = live.emit_snapshot("runner", seq=7, board=board)
            assert rec["kind"] == "ops_snapshot"
            assert rec["lane"] == "runner" and rec["seq"] == 7
            assert rec["rounds_per_s"] == 2.0
            assert bus.events("ops_snapshot")
        finally:
            obs.configure(None)


class TestFollowRotation:
    def _seed_run(self, tmp_path, events, gen1=None):
        with open(tmp_path / "metrics.jsonl", "w") as f:
            f.write(json.dumps({"_ts": 1.0, "iteration": 0, "round": 0,
                                "Test/Acc": 0.5}) + "\n")
        if gen1 is not None:
            with open(tmp_path / "events.jsonl.1", "w") as f:
                for e in gen1:
                    f.write(json.dumps(e) + "\n")
        with open(tmp_path / "events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")

    def test_follow_folds_existing_rotated_generation(self, tmp_path):
        from feddrift_tpu.obs.report import follow
        self._seed_run(
            tmp_path,
            gen1=[{"_ts": 1.0, "kind": "iteration_end", "iteration": 0,
                   "test_acc": 0.5, "rounds_per_s": 2.0}],
            events=[{"_ts": 2.0, "kind": "iteration_end", "iteration": 1,
                     "test_acc": 0.6, "rounds_per_s": 2.0},
                    {"_ts": 3.0, "kind": "run_end", "test_acc": 0.6}])
        buf = io.StringIO()
        assert follow(str(tmp_path), timeout_s=5, poll_s=0.05, out=buf) == 0
        out = buf.getvalue()
        assert "folded 1 events from rotated events.jsonl.1" in out
        assert "t=0 done" in out and "t=1 done" in out

    def test_follow_notes_mid_follow_rotation(self, tmp_path):
        """Rotate events.jsonl out from under a live follow: the reader
        must fold the unread tail from events.jsonl.1 (noting it) instead
        of silently losing it, then keep tailing the fresh file."""
        from feddrift_tpu.obs.report import follow
        path = tmp_path / "events.jsonl"
        filler = {"_ts": 1.1, "kind": "eval", "round": 0, "test_acc": 0.5,
                  "pad": "x" * 2000}
        self._seed_run(tmp_path, events=[
            {"_ts": 1.0, "kind": "iteration_end", "iteration": 0,
             "test_acc": 0.5, "rounds_per_s": 2.0}, filler])
        buf = io.StringIO()
        t = threading.Thread(target=follow, args=(str(tmp_path),),
                             kwargs=dict(timeout_s=20, poll_s=0.05, out=buf))
        t.start()
        time.sleep(0.5)                       # follow has read past 0
        os.replace(path, tmp_path / "events.jsonl.1")   # rotation
        with open(path, "w") as f:
            f.write(json.dumps({"_ts": 2.0, "kind": "iteration_end",
                                "iteration": 1, "test_acc": 0.6,
                                "rounds_per_s": 2.0}) + "\n")
            f.write(json.dumps({"_ts": 3.0, "kind": "run_end",
                                "test_acc": 0.6}) + "\n")
        t.join(timeout=20)
        assert not t.is_alive()
        out = buf.getvalue()
        assert "rotated mid-follow" in out
        assert "t=0 done" in out and "t=1 done" in out

    def test_summarize_folds_rotated_generation(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        self._seed_run(
            tmp_path,
            gen1=[{"_ts": 0.5, "kind": "drift_detected", "iteration": 0,
                   "client": 3, "acc_drop": 0.2},
                  {"_ts": 1.0, "kind": "iteration_end", "iteration": 0,
                   "wall_s": 1.0, "rounds": 2}],
            events=[{"_ts": 2.0, "kind": "iteration_end", "iteration": 1,
                     "wall_s": 1.0, "rounds": 2},
                    {"_ts": 3.0, "kind": "run_end", "test_acc": 0.6}])
        assert main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        # the drift event lives only in the rotated generation
        assert "drift_detected" in out


@pytest.mark.slow
class TestExperimentOpsEndToEnd:
    def test_run_serves_endpoints_and_snapshots(self, tmp_path):
        """A real (tiny) run with the ops plane on: endpoints answer
        while the process is live, the sketch reaches /metrics, and
        ops_snapshot events land in events.jsonl."""
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment
        out = str(tmp_path / "run")
        cfg = ExperimentConfig(
            dataset="sea", model="lr", concept_drift_algo="oblivious",
            concept_drift_algo_arg="", concept_num=1,
            client_num_in_total=8, client_num_per_round=8,
            train_iterations=3, comm_round=4, epochs=1, batch_size=50,
            sample_num=50, frequency_of_the_test=2, seed=0,
            ops_port=-1, slo_rounds_per_s=0.001, out_dir=out)
        exp = Experiment(cfg, out_dir=out)
        assert exp.ops is not None and exp.slo is not None
        try:
            exp.run()
            code, body = _get(exp.ops.url + "/metrics")
            assert code == 200
            assert b'round_wall_seconds_q{quantile="0.99"}' in body
            assert b"dispatch_gap_seconds_q" in body
            code, body = _get(exp.ops.url + "/healthz")
            doc = json.loads(body)
            assert code == 200 and doc["status"] == "ok"
            code, body = _get(exp.ops.url + "/status")
            doc = json.loads(body)
            assert doc["rounds_per_s"] is not None
            assert doc["run_phase"] == "done"
            live_p99 = doc["quantiles"]["round_wall_seconds_q"]["0.99"]
            assert live_p99 is not None and live_p99 > 0
        finally:
            exp.ops.close()
        kinds = [json.loads(l)["kind"]
                 for l in open(os.path.join(out, "events.jsonl"))]
        assert "ops_snapshot" in kinds
