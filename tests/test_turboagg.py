"""Turbo-Aggregate ring protocol: secure sum == plaintext sum, with and
without dropouts (VERDICT round-1 item 9; reference scaffold
TA_Aggregator.py / mpc_function.py)."""

import numpy as np
import pytest

from feddrift_tpu.platform.turboagg import (
    RingConfig, TurboAggregateRing, secure_federated_mean)


def _vectors(c, d, seed=0):
    return np.random.default_rng(seed).normal(size=(c, d)).astype(np.float64)


def test_secure_sum_matches_plaintext_no_dropouts():
    v = _vectors(12, 33)
    ring = TurboAggregateRing(RingConfig(num_clients=12, group_size=4,
                                         privacy_t=1))
    total, contributors = ring.aggregate(v)
    assert sorted(contributors) == list(range(12))
    np.testing.assert_allclose(total, v.sum(axis=0), atol=1e-3)


def test_secure_sum_under_dropouts():
    """k dropouts across stages: before_send clients are excluded,
    after_send clients included, and the ring completes either way."""
    v = _vectors(12, 17, seed=3)
    ring = TurboAggregateRing(RingConfig(num_clients=12, group_size=4,
                                         privacy_t=1))
    dropped = {2: "before_send",   # group 0: data never enters
               5: "after_send",    # group 1: counted, relay recovered
               9: "after_send"}    # group 2: counted
    total, contributors = ring.aggregate(v, dropped)
    expect_ids = [i for i in range(12) if i != 2]
    assert sorted(contributors) == expect_ids
    np.testing.assert_allclose(total, v[expect_ids].sum(axis=0), atol=1e-3)


@pytest.mark.parametrize("c", [1, 3, 5, 9, 13])
def test_ragged_population_folds_into_last_group(c):
    """C not divisible by group_size: the remainder folds into the last
    group as contributors-only, so aggregation works with no dropouts and
    with an early dropout."""
    v = _vectors(c, 7, seed=c)
    cfg = RingConfig(num_clients=c, group_size=4, privacy_t=1)
    total, contributors = TurboAggregateRing(cfg).aggregate(v)
    assert sorted(contributors) == list(range(c))
    np.testing.assert_allclose(total, v.sum(axis=0), atol=1e-3)
    if c > 1:
        total, contributors = TurboAggregateRing(cfg).aggregate(
            v, {c - 1: "before_send"})
        np.testing.assert_allclose(total, v[: c - 1].sum(axis=0), atol=1e-3)


def test_max_tolerable_dropouts_per_group():
    """n - T - 1 relays of one group may die; one more is unrecoverable."""
    cfg = RingConfig(num_clients=8, group_size=4, privacy_t=1)
    v = _vectors(8, 5, seed=1)
    # group 1 = clients 4..7; kill n-T-1 = 2 of them after send: fine.
    ok = {4: "after_send", 5: "after_send"}
    total, contributors = TurboAggregateRing(cfg).aggregate(v, ok)
    np.testing.assert_allclose(total, v.sum(axis=0), atol=1e-3)
    # a third dead relay in the same group leaves < T+1 alive positions.
    bad = {4: "after_send", 5: "after_send", 6: "after_send"}
    with pytest.raises(RuntimeError, match="unrecoverable"):
        TurboAggregateRing(cfg).aggregate(v, bad)


def test_single_share_is_masked():
    """Privacy smoke: one position's share of a constant vector is not the
    vector (degree-T randomness masks it)."""
    cfg = RingConfig(num_clients=4, group_size=4, privacy_t=1)
    from feddrift_tpu.platform.secure_agg import bgw_encode, quantize
    rng = np.random.default_rng(0)
    q = quantize(np.full(6, 0.5))[None, :]
    shares = bgw_encode(q, cfg.group_size, cfg.privacy_t, cfg.p, rng)
    assert not np.array_equal(shares[0, 0], q[0])
    # shares differ per position (nonconstant polynomial w.h.p.)
    assert not np.array_equal(shares[0, 0], shares[1, 0])


def test_secure_federated_mean_weighted():
    v = _vectors(8, 9, seed=7)
    w = np.array([1, 2, 3, 4, 1, 2, 3, 4], np.float64)
    got = secure_federated_mean(v, w, RingConfig(num_clients=8, group_size=4))
    expect = (v * w[:, None]).sum(0) / w.sum()
    np.testing.assert_allclose(got, expect, atol=1e-3)


def test_secure_federated_mean_sample_count_weights():
    """Realistic sample-count weights (thousands per client) must not wrap
    the field: weights are normalised before quantization."""
    v = _vectors(8, 9, seed=11)
    w = np.full(8, 5000.0)
    got = secure_federated_mean(v, w, RingConfig(num_clients=8, group_size=4))
    np.testing.assert_allclose(got, v.mean(0), atol=1e-3)
    with pytest.raises(ValueError, match="non-negative"):
        secure_federated_mean(v, -w)


def test_secure_federated_mean_excludes_early_dropout():
    v = _vectors(6, 4, seed=9)
    w = np.ones(6)
    got = secure_federated_mean(
        v, w, RingConfig(num_clients=6, group_size=3),
        dropped={1: "before_send"})
    keep = [0, 2, 3, 4, 5]
    np.testing.assert_allclose(got, v[keep].mean(0), atol=1e-3)


def test_ring_config_validation():
    with pytest.raises(ValueError, match="group_size"):
        RingConfig(num_clients=4, group_size=2, privacy_t=1)
    with pytest.raises(ValueError, match="unknown client"):
        TurboAggregateRing(RingConfig(num_clients=4)).aggregate(
            _vectors(4, 3), {99: "after_send"})
    with pytest.raises(ValueError, match="stage"):
        TurboAggregateRing(RingConfig(num_clients=4)).aggregate(
            _vectors(4, 3), {1: "mid_send"})
