"""Ring attention + long-context training tests on the 8-device CPU mesh.

Parity bar: ring attention over a sharded sequence must match naive full
attention to float tolerance, forward AND backward; the sharded long-context
train step must match the unsharded reference step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def naive_attention(q, k, v, causal=True):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(D)
    if causal:
        L = q.shape[2]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _qkv(key, B=2, H=2, L=64, D=8):
    ks = jax.random.split(key, 3)
    shape = (B, H, L, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


class TestBlockwise:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_naive(self, causal):
        from feddrift_tpu.parallel.ring_attention import blockwise_attention
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = blockwise_attention(q, k, v, causal=causal, block_size=16)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(naive_attention(q, k, v, causal)),
                                   atol=1e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("L", [65, 100, 17])
    def test_non_divisible_length(self, causal, L):
        # regression: L not a multiple of block_size must pad+mask, not crash
        from feddrift_tpu.parallel.ring_attention import blockwise_attention
        q, k, v = _qkv(jax.random.PRNGKey(1), L=L)
        out = blockwise_attention(q, k, v, causal=causal, block_size=32)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(naive_attention(q, k, v, causal)),
                                   atol=1e-5)


class TestRing:
    def _mesh(self, n):
        devs = np.asarray(jax.devices()[:n]).reshape(1, n)
        return Mesh(devs, ("data", "seq"))

    @pytest.mark.parametrize("n_seq", [2, 4, 8])
    def test_forward_matches_naive(self, n_seq):
        from feddrift_tpu.parallel.ring_attention import ring_attention
        mesh = self._mesh(n_seq)
        q, k, v = _qkv(jax.random.PRNGKey(1), L=64)

        def local(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True)

        fn = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"), check_vma=False))
        out = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(naive_attention(q, k, v, True)),
                                   atol=1e-5)

    def test_gradient_matches_naive(self):
        from feddrift_tpu.parallel.ring_attention import ring_attention
        mesh = self._mesh(4)
        q, k, v = _qkv(jax.random.PRNGKey(2), L=32)

        def ring_loss(q, k, v):
            def local(q, k, v):
                out = ring_attention(q, k, v, axis_name="seq", causal=True)
                return jax.lax.psum(jnp.sum(out ** 2), "seq")
            fn = jax.shard_map(local, mesh=mesh,
                               in_specs=(P(None, None, "seq"),) * 3,
                               out_specs=P(),
                               check_vma=False)
            return fn(q, k, v)

        def naive_loss(q, k, v):
            return jnp.sum(naive_attention(q, k, v, True) ** 2)

        g_ring = jax.jit(jax.grad(lambda *a: jnp.sum(ring_loss(*a))))(q, k, v)
        g_naive = jax.grad(naive_loss)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_naive),
                                   atol=2e-4)


class TestLongContext:
    def test_sharded_step_matches_reference_and_learns(self):
        from feddrift_tpu.parallel.longcontext import (LongContextTrainer,
                                                       place_batch)
        devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("data", "seq"))
        tr = LongContextTrainer(vocab_size=32, d_model=32, num_heads=2,
                                num_layers=2, max_len=256, lr=1e-2)
        rng = np.random.default_rng(0)
        # periodic token stream -> easily learnable next-token task
        base = np.tile(np.arange(32, dtype=np.int32), 9)
        tokens = np.stack([base[i: i + 256] for i in range(4)])
        labels = np.stack([base[i + 1: i + 257] for i in range(4)])

        params, opt_state = tr.init(jax.random.PRNGKey(0),
                                    jnp.asarray(tokens[:1, :64]))
        # forward parity sharded vs reference
        fwd = tr.make_forward(mesh)
        t_dev, l_dev = place_batch(mesh, jnp.asarray(tokens), jnp.asarray(labels))
        out_sharded = np.asarray(fwd(params, t_dev))
        out_ref = np.asarray(tr.reference_forward(params, jnp.asarray(tokens)))
        np.testing.assert_allclose(out_sharded, out_ref, atol=2e-4)

        step = tr.make_train_step(mesh)
        losses = []
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, t_dev, l_dev)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_transformer_in_drift_pipeline(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import run_experiment
        cfg = ExperimentConfig(
            dataset="shakespeare", model="transformer",
            concept_drift_algo="win-1", train_iterations=2, comm_round=4,
            epochs=2, sample_num=32, batch_size=16, frequency_of_the_test=2,
            lr=0.003, client_num_in_total=8, client_num_per_round=8, seed=0)
        exp = run_experiment(cfg)
        assert exp.logger.last("Test/Acc") is not None
