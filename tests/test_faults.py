"""Fault injection + failure detection (platform/faults.py).

The reference hangs forever on a dead client (SURVEY.md §5); here failures
must degrade gracefully: masked training, non-blocking detection.
"""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.platform.faults import FailureDetector, FaultInjector
from feddrift_tpu.simulation.runner import run_experiment

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


class TestFaultInjector:
    def test_deterministic_masks(self):
        a = FaultInjector(8, 0.3, seed=1).masks(range(20))
        b = FaultInjector(8, 0.3, seed=1).masks(range(20))
        np.testing.assert_array_equal(a, b)
        assert 0 < a.mean() < 1   # some dropouts, not all

    def test_kill_is_permanent_and_revivable(self):
        inj = FaultInjector(4, 0.0)
        inj.kill(2)
        m = inj.masks(range(5))
        assert (m[:, 2] == 0).all() and (m[:, [0, 1, 3]] == 1).all()
        inj.revive(2)
        assert inj.mask(9)[2] == 1

    def test_quorum_of_one_floor(self):
        inj = FaultInjector(3, 0.99, seed=0)
        m = inj.masks(range(50))
        assert (m.sum(axis=1) >= 1).all()

    def test_rejects_bad_prob(self):
        with pytest.raises(ValueError):
            FaultInjector(4, 1.0)


class TestFailureDetector:
    def test_flags_after_patience(self):
        det = FailureDetector(4, patience=3)
        alive = np.ones(4)
        dead2 = alive.copy()
        dead2[2] = 0
        det.observe(dead2)
        det.observe(dead2)
        assert det.suspected.tolist() == []
        det.observe(dead2)
        assert det.suspected.tolist() == [2]
        det.observe(alive)   # client comes back -> cleared
        assert det.suspected.tolist() == []
        assert det.summary()["rounds_seen"] == 4


class TestEndToEndWithFaults:
    def _cfg(self, **kw):
        base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                    train_iterations=2, comm_round=10, epochs=3, sample_num=80,
                    batch_size=40, frequency_of_the_test=5, lr=0.05,
                    client_num_in_total=8, client_num_per_round=8, seed=0)
        base.update(kw)
        return ExperimentConfig(**base)

    def test_training_survives_dropout(self):
        exp = run_experiment(self._cfg(fault_dropout_prob=0.4))
        assert exp.logger.last("Test/Acc") > 0.6
        # detector observed every round of both iterations
        assert exp.failure_detector.rounds_seen == 20

    def test_dropout_changes_trajectory_deterministically(self):
        a = run_experiment(self._cfg(fault_dropout_prob=0.4)).logger.series("Test/Acc")
        b = run_experiment(self._cfg(fault_dropout_prob=0.4)).logger.series("Test/Acc")
        c = run_experiment(self._cfg()).logger.series("Test/Acc")
        assert a == b
        assert a != c

    def test_composes_with_client_sampling(self):
        exp = run_experiment(self._cfg(client_num_per_round=4,
                                       fault_dropout_prob=0.3))
        assert exp.logger.last("Test/Acc") > 0.55

    def test_nonselection_is_not_failure(self):
        # heavy subsampling with zero faults: detector must suspect no one
        # (non-selection carries no liveness signal)
        exp = run_experiment(self._cfg(client_num_per_round=2,
                                       fault_enabled=True))
        assert exp.failure_detector.suspected.tolist() == []

    def test_dead_client_detected_under_subsampling(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment
        exp = Experiment(self._cfg(client_num_per_round=4,
                                   fault_enabled=True,
                                   failure_patience=2))
        exp.fault_injector.kill(3)
        exp.run()
        assert 3 in exp.failure_detector.suspected.tolist()

    def test_observed_mask_freezes_streak(self):
        det = FailureDetector(3, patience=2)
        det.observe([0, 1, 1], observed=[True, True, False])
        det.observe([0, 1, 1], observed=[False, True, True])
        # client 0: absent once then unobserved -> streak stays 1, no suspect
        assert det.suspected.tolist() == []
        det.observe([0, 1, 1], observed=[True, True, True])
        assert det.suspected.tolist() == [0]
