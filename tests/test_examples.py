"""The examples must stay runnable: they are the documented plugin surface."""

import sys
from pathlib import Path
import pytest

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))


class TestCustomPlugin:
    def test_custom_dataset_and_model_compose_with_feddrift(self):
        import custom_plugin
        acc = custom_plugin.main(smoke=True)
        # drifting 2-class problem: anything clearly above chance proves the
        # pipeline trained; exact accuracy is not the example's point
        assert acc > 0.6, acc

    def test_registries_expose_plugins(self):
        import custom_plugin  # noqa: F401  (import registers)
        from feddrift_tpu.data.registry import available_datasets
        from feddrift_tpu.models import available_models
        assert "xor-rot" in available_datasets()
        assert "tiny-mlp" in available_models()
