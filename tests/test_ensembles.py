"""Tests for AUE / AUE-PC / KUE / DriftSurf / MultiModel / Ada / ClusterFL.

Unit tests pin the deterministic math (AUE weight formula, kappa, Ada eta
recursion, DriftSurf transitions); e2e smoke runs exercise every algorithm
through the full jitted round loop on the 8-device CPU mesh, mirroring the
reference's --ci smoke strategy (SURVEY.md §4).
"""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="aue",
                train_iterations=3, comm_round=12, epochs=5, sample_num=100,
                batch_size=50, frequency_of_the_test=5, lr=0.05,
                client_num_in_total=10, client_num_per_round=10, seed=0,
                concept_num=2, ensemble_window=3)
    base.update(kw)
    return ExperimentConfig(**base)


class TestAue:
    def test_window_growth_and_shift(self):
        exp = Experiment(_cfg(train_iterations=2))
        algo = exp.algo
        algo.begin_iteration(0)
        assert algo.model_num == 1
        tw = np.asarray(algo.round_inputs(0, 0)[0])
        assert tw[0, 0, 0] == 1.0 and tw[1].sum() == 0   # only model 0 active
        exp.run_iteration(0)
        p0 = exp.pool.slot(0)
        algo.begin_iteration(1)
        assert algo.model_num == 2
        # circular reload: model 1 inherits model 0's params; model 0 reinit
        np.testing.assert_allclose(
            np.asarray(exp.pool.slot(1)["dense"]["kernel"] if isinstance(exp.pool.slot(1), dict) and "dense" in exp.pool.slot(1) else list(exp.pool.slot(1).values())[0]["kernel"]),
            np.asarray(list(p0.values())[0]["kernel"]))
        tw = np.asarray(algo.round_inputs(1, 0)[0])
        assert tw[0, 0, 1] == 1.0 and tw[0, 0, 0] == 0.0   # model 0: win-1
        assert tw[1, 0, 0] == 1.0 and tw[1, 0, 1] == 1.0   # model 1: win-2

    def test_ens_weights_favor_accurate_model(self):
        exp = run_experiment(_cfg(train_iterations=2, comm_round=10))
        w = exp.algo.ens_weights
        assert w.shape == (3,)
        assert abs(w.sum() - 1.0) < 1e-6
        assert exp.logger.last("Test/Acc") > 0.6

    def test_auepc_per_client_weights(self):
        exp = run_experiment(_cfg(concept_drift_algo="auepc",
                                  train_iterations=2, comm_round=10))
        assert exp.algo.ens_weights.shape == (10, 3)
        np.testing.assert_allclose(exp.algo.ens_weights.sum(axis=1), 1.0,
                                   rtol=1e-5)
        assert exp.logger.last("Test/Acc") > 0.6


class TestKue:
    def test_masks_valid(self):
        exp = Experiment(_cfg(concept_drift_algo="kue", concept_num=4))
        masks = exp.algo.masks
        assert masks.shape[0] == 4
        assert ((masks == 0) | (masks == 1)).all()
        assert (masks.sum(axis=1) >= 1).all()      # every model >= 1 feature

    def test_kappa_matches_sklearn(self):
        # golden cross-check of the production kappa implementation against
        # sklearn on random labelings
        from sklearn.metrics import cohen_kappa_score
        from feddrift_tpu.algorithms.ensembles import kappa_from_confusion
        rng = np.random.default_rng(0)
        K = 4
        for trial in range(5):
            y_true = rng.integers(0, K, size=400)
            y_pred = np.where(rng.random(400) < 0.6, y_true,
                              rng.integers(0, K, size=400))
            A = np.zeros((K, K))
            np.add.at(A, (y_true, y_pred), 1.0)
            expected = cohen_kappa_score(y_true, y_pred)
            assert abs(kappa_from_confusion(A) - expected) < 1e-9
        # degenerate matrix (zero denominator): guard returns 0, not NaN
        assert kappa_from_confusion(np.full((2, 2), 0.0)) == 0.0
        assert kappa_from_confusion(np.array([[5.0, 0.0], [0.0, 0.0]])) == 0.0

    def test_kappa_formula(self):
        # Perfect predictions -> kappa 1; uniform-random-ish -> ~0.
        A = np.eye(3) * 10.0
        n = A.sum(); left = np.trace(A)
        right = (A.sum(1) * A.sum(0)).sum()
        kappa = (n * left - right) / (n * n - right)
        assert kappa == pytest.approx(1.0)

    def test_e2e_smoke(self):
        exp = run_experiment(_cfg(concept_drift_algo="kue", concept_num=3,
                                  train_iterations=2, comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.5
        assert 0 <= exp.algo.worst_idx < 3


class TestDriftSurf:
    def test_transitions_on_synthetic_accuracy(self):
        exp = Experiment(_cfg(concept_drift_algo="driftsurf"))
        a = exp.algo
        assert a.state == "stab" and a.train_keys == ["pred", "stab"]
        # force a drift signal: pretend pred accuracy collapsed
        a.acc_best = 0.95
        a._score = lambda key, t: 0.5
        a._run_ds_algo(1)
        assert a.state == "reac"
        assert a.train_keys == ["pred", "reac"]
        a._run_ds_algo(2)
        a._run_ds_algo(3)   # reac_ctr hits reac_len=3 -> exit
        assert a.state == "stab"

    def test_e2e_tracks_drift(self):
        exp = run_experiment(_cfg(concept_drift_algo="driftsurf",
                                  train_iterations=3, comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.5
        idx = exp.algo.test_model_idx(2)
        assert idx.shape == (10,)


class TestMultiModel:
    def test_mmacc_spawns_on_drift(self):
        exp = run_experiment(_cfg(concept_drift_algo="mmacc",
                                  train_iterations=3, comm_round=12,
                                  concept_num=2))
        a = exp.algo
        # preset A flips half the clients at step 2 -> second model appears
        assert len(a._assigned()) >= 1
        assert exp.logger.last("Test/Acc") > 0.5

    def test_mmgeni_follows_oracle(self):
        exp = run_experiment(_cfg(concept_drift_algo="mmgeni",
                                  train_iterations=3, comm_round=10,
                                  concept_num=2))
        a = exp.algo
        np.testing.assert_array_equal(
            a.test_model_idx(2), a.concepts[2] % 2)
        assert exp.logger.last("Test/Acc") > 0.6

    def test_mmgeniex_predicts_test_model(self):
        exp = run_experiment(_cfg(concept_drift_algo="mmgeniex",
                                  train_iterations=3, comm_round=10,
                                  concept_num=2))
        a = exp.algo
        drift_steps = np.nonzero(a.concepts.any(axis=1))[0]
        t = 2
        if t >= drift_steps[0]:
            np.testing.assert_array_equal(a.test_model_idx(t),
                                          a.concepts[t + 1] % 2)


class TestAda:
    def test_eta_recursion_decreases(self):
        exp = Experiment(_cfg(concept_drift_algo="ada",
                              concept_drift_algo_arg="win-1_round"))
        a = exp.algo
        rng = np.random.default_rng(0)
        theta = rng.normal(size=100)
        for t in range(5):
            a._ada_update(theta + 0.01 * rng.normal(size=100), t)
        assert a.eta <= a.init_lr
        assert a.eta > 0

    def test_e2e_round_mode(self):
        exp = run_experiment(_cfg(concept_drift_algo="ada",
                                  concept_drift_algo_arg="win-1_round",
                                  train_iterations=2, comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.6

    def test_e2e_iter_mode(self):
        exp = run_experiment(_cfg(concept_drift_algo="ada",
                                  concept_drift_algo_arg="all_iter",
                                  train_iterations=2, comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.6


class TestLegacyClusterFL:
    def test_e2e_smoke(self):
        # comm_round small so no split fires (gate needs r > 100); the point
        # is the gating/norm machinery runs under jit without error.
        exp = run_experiment(_cfg(concept_drift_algo="clusterfl",
                                  concept_drift_algo_arg="win-1",
                                  train_iterations=2, comm_round=8))
        assert not exp.algo.is_split
        assert exp.logger.last("Test/Acc") > 0.5

    def test_split_machinery(self):
        exp = Experiment(_cfg(concept_drift_algo="clusterfl",
                              concept_drift_algo_arg="win-1"))
        a = exp.algo
        a.begin_iteration(0)
        assert (a.assignment == 0).all()
        tw = np.asarray(a.round_inputs(0, 0)[0])
        assert tw[0, :, 0].sum() == 10      # everyone on model 0


class TestStatePersistence:
    @pytest.mark.parametrize("algo,arg", [
        ("aue", ""), ("kue", ""), ("driftsurf", ""), ("mmacc", ""),
        ("ada", "win-1_round")])
    def test_state_roundtrip(self, algo, arg):
        exp = Experiment(_cfg(concept_drift_algo=algo,
                              concept_drift_algo_arg=arg, train_iterations=2,
                              comm_round=4))
        exp.run_iteration(0)
        d = exp.algo.state_dict()
        exp2 = Experiment(_cfg(concept_drift_algo=algo,
                               concept_drift_algo_arg=arg, train_iterations=2,
                               comm_round=4))
        exp2.algo.load_state_dict(d)
