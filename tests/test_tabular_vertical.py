"""Tabular (UCI SUSY/RO, stackoverflow_lr) datasets + party-split VFL data.

Covers SURVEY.md §2b #35's remaining loaders and their composition with the
drift pipeline and the vertical-FL trainer.
"""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.data.vertical_data import (
    LENDING_LOAN_DIM, LENDING_QUAL_DIM, NUS_WIDE_XA_DIM, NUS_WIDE_XB_DIM,
    load_lending_club, load_nus_wide)


def _cfg(name, **kw):
    return ExperimentConfig(dataset=name, model="lr", train_iterations=3,
                            client_num_in_total=4, client_num_per_round=4,
                            sample_num=40, concept_num=2, change_points="A",
                            **kw)


class TestUciDrift:
    @pytest.mark.parametrize("name,dim", [("susy", 18), ("ro", 5)])
    def test_shapes_and_determinism(self, name, dim):
        ds1 = make_dataset(_cfg(name))
        ds2 = make_dataset(_cfg(name))
        assert ds1.x.shape == (4, 4, 40, dim)
        assert ds1.num_classes == 2
        np.testing.assert_array_equal(ds1.x, ds2.x)
        np.testing.assert_array_equal(ds1.y, ds2.y)

    def test_concepts_are_different_functions(self):
        # Same features relabeled under concept k's hyperplane: labels at a
        # drifted (t, c) cell must disagree materially with concept 0's.
        from feddrift_tpu.data.tabular import generate_uci_drift
        cp = np.zeros((4, 4), dtype=np.int64)
        drifted = cp.copy()
        drifted[2:, :] = 1
        base = generate_uci_drift("susy", cp, 3, 4, 200, seed=5)
        drift = generate_uci_drift("susy", drifted, 3, 4, 200, seed=5)
        same = (base.y[0, 0] == drift.y[0, 0]).mean()
        changed = (base.y[0, 3] == drift.y[0, 3]).mean()
        assert same == 1.0
        assert changed < 0.9  # boundary rotation relabels a chunk


class TestStackoverflowLr:
    def test_bag_of_words_learnable(self):
        ds = make_dataset(_cfg("stackoverflow_lr"))
        assert ds.x.shape == (4, 4, 40, 1000)
        assert ds.num_classes == 50
        # word counts: nonnegative integers summing to the 30 drawn tokens
        assert (ds.x >= 0).all()
        np.testing.assert_allclose(ds.x.sum(-1), 30.0)

    def test_drift_permutes_tags(self):
        from feddrift_tpu.data.tabular import generate_stackoverflow_lr_drift
        cp = np.zeros((4, 2), dtype=np.int64)
        drifted = cp.copy()
        drifted[2:, :] = 1
        base = generate_stackoverflow_lr_drift(cp, 3, 2, 150, seed=3)
        drift = generate_stackoverflow_lr_drift(drifted, 3, 2, 150, seed=3)
        # identical topic draws; labels at a drifted cell follow the permuted
        # tag map, so most must differ from concept 0's
        assert (base.y[0, 0] == drift.y[0, 0]).all()
        assert (base.y[0, 3] == drift.y[0, 3]).mean() < 0.1


class TestVerticalData:
    def test_nus_wide_dims(self):
        ps, y = load_nus_wide(n_samples=64)
        assert [p.shape for p in ps] == [(64, NUS_WIDE_XA_DIM),
                                        (64, NUS_WIDE_XB_DIM)]
        ps3, _ = load_nus_wide(n_samples=64, num_parties=3)
        assert len(ps3) == 3 and ps3[0].shape[1] + ps3[1].shape[1] == NUS_WIDE_XA_DIM

    def test_lending_club_dims(self):
        ps, y = load_lending_club(n_samples=64)
        assert [p.shape for p in ps] == [(64, LENDING_QUAL_DIM),
                                        (64, LENDING_LOAN_DIM)]
        assert set(np.unique(y)) <= {0, 1}

    def test_vfl_trains_on_lending_club(self):
        import jax
        import jax.numpy as jnp
        import optax

        from feddrift_tpu.platform.vertical import VflTrainer, make_linear_party

        (xq, xl), y = load_lending_club(n_samples=256, seed=1)
        xq, xl = jnp.asarray(xq), jnp.asarray(xl)
        guest = make_linear_party(LENDING_QUAL_DIM)
        host = make_linear_party(LENDING_LOAN_DIM)
        gp = guest.init(jax.random.PRNGKey(0), xq[:2])["params"]
        hp = host.init(jax.random.PRNGKey(1), xl[:2])["params"]
        tr = VflTrainer(
            guest_apply=lambda p, xx: guest.apply({"params": p}, xx),
            host_applies=[lambda p, xx: host.apply({"params": p}, xx)],
            optimizer=optax.sgd(0.5))
        g_opt, h_opts = tr.init_states(gp, [hp])
        yf = jnp.asarray(y.astype(np.float32))
        first = None
        for _ in range(60):
            gp, hps, g_opt, h_opts, loss = tr.train_step(
                gp, [hp], g_opt, h_opts, xq, [xl], yf)
            hp = hps[0]
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.9
        preds = tr.predict(gp, [hp], xq, [xl])
        acc = ((np.asarray(preds) > 0.5) == y).mean()
        assert acc > 0.7
