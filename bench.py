"""Benchmark: FedDrift canonical config throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config: the reference's canonical run (README.md:46-50): SEA-4, 10 clients,
fnn, 200 rounds x 5 local steps per time step, batch 500, lr 0.01, 500
samples/client/step. We measure steady-state communication-round throughput
(train_round + the periodic eval), which is the quantity the reference logs
per round ("aggregate time cost", FedAvgEnsAggregatorSoftCluster.py:193-194).

Baseline: the reference publishes no numbers (BASELINE.md). Its round time is
bounded below by its 0.3 s communication polling alone
(mpi_send_thread.py:29, com_manager.py:78) plus pickling M state_dicts per
client and serial M x C evaluation; we take 1.0 rounds/s as a *generous*
reference estimate on its 4-GPU setup, and report vs_baseline against it.
Run with --smoke for a fast CI-sized check.
"""

from __future__ import annotations

import json
import sys
import time

import jax

REFERENCE_ROUNDS_PER_SEC = 1.0  # generous estimate; see module docstring


def _probe_backend(timeout_s: float = 90.0) -> str:
    """Return the usable backend name, falling back to CPU if the default
    backend is unreachable.

    The axon TPU tunnel can hang indefinitely at client creation when the
    remote side is unhealthy; a hung benchmark records nothing. The probe
    runs in a SUBPROCESS (an in-process thread would wedge this process:
    backend creation holds jax's global init lock, so once a thread hangs in
    it no other thread can create any backend). On timeout the main process
    — which has not initialized any backend yet — pins the CPU platform.
    """
    import subprocess

    why = f"probe timed out after {timeout_s:.0f}s"
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)));"
             "print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1]
        why = (f"probe exited {out.returncode}: "
               + (out.stderr or "").strip()[-500:])
    except subprocess.TimeoutExpired:
        pass
    jax.config.update("jax_platforms", "cpu")
    print(json.dumps({"warning": f"default backend unreachable ({why}); "
                      "benchmarking on CPU fallback"}),
          file=sys.stderr)
    return "cpu-fallback"


def _enable_compile_cache() -> None:
    """Persist compiled executables across processes (~20-40s saved per
    program on repeat benchmark runs; cache is keyed by platform + HLO)."""
    import os
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:                   # cache is an optimization only
        print(json.dumps({"warning": f"compile cache unavailable: {e}"}),
              file=sys.stderr)


def main() -> None:
    smoke = "--smoke" in sys.argv
    backend = _probe_backend()
    _enable_compile_cache()

    from feddrift_tpu.config import ExperimentConfig
    from feddrift_tpu.simulation.runner import Experiment

    algo = "softcluster"
    from feddrift_tpu.algorithms import available_algorithms
    if "softcluster" not in available_algorithms():
        algo = "win-1"   # pre-softcluster fallback

    cfg = ExperimentConfig(
        dataset="sea", model="fnn", concept_drift_algo=algo,
        concept_drift_algo_arg="H_A_C_1_10_0", concept_num=4,
        change_points="A",
        client_num_in_total=10, client_num_per_round=10,
        train_iterations=3 if smoke else 10,
        comm_round=20 if smoke else 200,
        epochs=5, batch_size=500, sample_num=100 if smoke else 500,
        lr=0.01, frequency_of_the_test=10,
        report_client=0,
    )
    exp = Experiment(cfg)

    # Warm-up: run time steps 0 AND 1 fully — t=0 takes the cluster_init
    # branch only; t>=1 is the first to trace acc_cells / the hierarchical
    # merge path, so steady-state timing must start at t=2.
    exp.run_iteration(0)
    exp.run_iteration(1)

    # Timed steady state: the remaining time steps.
    t0 = time.time()
    for t in range(2, cfg.train_iterations):
        exp.run_iteration(t)
    jax.block_until_ready(exp.pool.params)
    elapsed = time.time() - t0
    rounds = cfg.comm_round * (cfg.train_iterations - 2)
    rps = rounds / elapsed

    final_acc = exp.logger.last("Test/Acc")
    print(json.dumps({
        "metric": f"FedDrift SEA-4 round throughput ({algo}, 10 clients, "
                  f"M=4, fnn, batch 500)",
        "value": round(rps, 3),
        "unit": "rounds/s",
        "vs_baseline": round(rps / REFERENCE_ROUNDS_PER_SEC, 3),
        "final_test_acc": round(float(final_acc), 4),
        "wall_s": round(elapsed, 2),
        "rounds": rounds,
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
