"""Benchmark: FedDrift canonical config throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Config: the reference's canonical run (README.md:46-50): SEA-4, 10 clients,
fnn, 200 rounds x 5 local steps per time step, batch 500, lr 0.01, 500
samples/client/step. We measure steady-state communication-round throughput
(train_round + the periodic eval), which is the quantity the reference logs
per round ("aggregate time cost", FedAvgEnsAggregatorSoftCluster.py:193-194).

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured, not assumed: before the timed run we execute
the same canonical config on THIS HOST's CPU through the per-round
dispatch path (cfg.chunk_rounds=False — one host->device dispatch and one
eval fetch per round, the closest shape to the reference's per-round
message loop) for a short sample and extrapolate rounds/s.  The reported
ratio is therefore "device fused path vs this host's CPU per-round path";
it is an intra-framework speedup, NOT a measured reference-GPU comparison.
Run with --smoke for a fast CI-sized check.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# TPU v5 lite (v5e) peak: ~197 TFLOP/s bf16, ~98 TFLOP/s f32 per chip.
PEAK_FLOPS = {"tpu": {"bfloat16": 197e12, "float32": 98e12},
              "cpu": {"bfloat16": 5e10, "float32": 1e11}}


def _probe_backend(attempts: int = 3, timeout_s: float = 120.0):
    """Return (usable backend name, probe diagnosis list).

    The axon TPU tunnel can hang indefinitely at client creation when the
    remote side is unhealthy; a hung benchmark records nothing. Each probe
    runs in a SUBPROCESS (an in-process thread would wedge this process:
    backend creation holds jax's global init lock, so once a thread hangs in
    it no other thread can create any backend). The tunnel also flakes
    transiently, so we retry before falling back. On timeout the main
    process — which has not initialized any backend yet — pins the CPU
    platform; the per-attempt diagnosis is returned for the bench JSON.
    """
    import subprocess

    diagnosis = []
    for i in range(attempts):
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)));"
                 "print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s)
            if out.returncode == 0 and out.stdout.strip():
                backend = out.stdout.strip().splitlines()[-1]
                diagnosis.append(f"attempt {i}: ok ({backend}, "
                                 f"{time.time() - t0:.0f}s)")
                return backend, diagnosis
            diagnosis.append(
                f"attempt {i}: exited {out.returncode}: "
                + (out.stderr or "").strip()[-300:])
        except subprocess.TimeoutExpired:
            diagnosis.append(f"attempt {i}: timed out after {timeout_s:.0f}s")
    jax.config.update("jax_platforms", "cpu")
    print(json.dumps({"warning": "default backend unreachable; "
                      "benchmarking on CPU fallback",
                      "probe": diagnosis}), file=sys.stderr)
    return "cpu-fallback", diagnosis


def _enable_compile_cache() -> None:
    """Persist compiled executables across processes (~20-40s saved per
    program on repeat benchmark runs; cache is keyed by platform + HLO)."""
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:                   # cache is an optimization only
        print(json.dumps({"warning": f"compile cache unavailable: {e}"}),
              file=sys.stderr)


def _canonical_cfg(smoke: bool, **overrides):
    from feddrift_tpu.config import ExperimentConfig

    base = dict(
        dataset="sea", model="fnn", concept_drift_algo="softcluster",
        concept_drift_algo_arg="H_A_C_1_10_0", concept_num=4,
        change_points="A",
        client_num_in_total=10, client_num_per_round=10,
        train_iterations=3 if smoke else 10,
        comm_round=20 if smoke else 200,
        epochs=5, batch_size=500, sample_num=100 if smoke else 500,
        lr=0.01, frequency_of_the_test=10,
        report_client=0)
    base.update(overrides)
    return ExperimentConfig(**base)


def _flops_per_round(exp) -> float:
    """Analytic round-FLOPs estimate for the MFU line.

    Dense-model forward ~= 2 FLOPs per param per sample; backward ~= 2x
    forward. Per round: M x C local trainers each run `epochs` SGD steps on
    a `batch_size` batch. Eval matrices add M x C full-step inferences every
    frequency_of_the_test rounds (amortised in).
    """
    import numpy as np
    cfg, ds = exp.cfg, exp.ds
    n_params = sum(int(np.prod(l.shape[1:]))   # leading M axis excluded
                   for l in jax.tree_util.tree_leaves(exp.pool.params))
    M, C = exp.pool.num_models, cfg.client_num_in_total
    train = M * C * cfg.epochs * cfg.batch_size * (2 * n_params) * 3
    eval_amortised = (M * C * ds.samples_per_step * (2 * n_params)
                     / max(cfg.frequency_of_the_test, 1))
    return float(train + eval_amortised)


def _measure_cpu_baseline(smoke: bool) -> float | None:
    """Rounds/s of the canonical config on this host's CPU through the
    PER-ROUND dispatch path (chunk_rounds=False) — the measured stand-in
    for the reference's per-round message loop. Runs in a subprocess so the
    main process's backend choice (TPU) is untouched."""
    import subprocess

    code = (
        "import jax, json, time;"
        "jax.config.update('jax_platforms', 'cpu');"
        "import bench;"
        "bench._enable_compile_cache();"
        "from feddrift_tpu.simulation.runner import Experiment;"
        f"cfg = bench._canonical_cfg({smoke}, train_iterations=3, "
        "comm_round=20, chunk_rounds=False);"
        "exp = Experiment(cfg);"
        # warm-up t=0 AND t=1: t>=1 is the first trace of the acc_cells /
        # merge path (same reason the main measurement starts at t=2)
        "exp.run_iteration(0); exp.run_iteration(1);"
        "t0 = time.time(); exp.run_iteration(2);"
        "jax.block_until_ready(exp.pool.params);"
        "print(json.dumps({'rps': cfg.comm_round / (time.time() - t0)}))")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=1200,
                             cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(out.stdout.strip().splitlines()):
            try:
                return float(json.loads(line)["rps"])
            except (json.JSONDecodeError, KeyError):
                continue
        print(json.dumps({"warning": "cpu baseline produced no number",
                          "stderr": (out.stderr or "")[-300:]}),
              file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(json.dumps({"warning": "cpu baseline timed out"}),
              file=sys.stderr)
    return None


def main() -> None:
    smoke = "--smoke" in sys.argv
    if "--cpu" in sys.argv:       # explicit local run: skip the probe wait
        jax.config.update("jax_platforms", "cpu")
        backend, probe_diag = "cpu-forced", ["--cpu flag"]
    else:
        backend, probe_diag = _probe_backend()
    _enable_compile_cache()

    # Measured baseline (see module docstring). Skipped under --smoke (the
    # CI-sized check must stay fast; vs_baseline is reported null there).
    baseline_rps = None if smoke else _measure_cpu_baseline(smoke)

    from feddrift_tpu.simulation.runner import Experiment

    cfg = _canonical_cfg(smoke)
    exp = Experiment(cfg)

    # Warm-up: run time steps 0 AND 1 fully — t=0 takes the cluster_init
    # branch only; t>=1 is the first to trace acc_cells / the hierarchical
    # merge path, so steady-state timing must start at t=2.
    exp.run_iteration(0)
    exp.run_iteration(1)

    # Timed steady state: the remaining time steps.
    t0 = time.time()
    for t in range(2, cfg.train_iterations):
        exp.run_iteration(t)
    jax.block_until_ready(exp.pool.params)
    elapsed = time.time() - t0
    rounds = cfg.comm_round * (cfg.train_iterations - 2)
    rps = rounds / elapsed

    dtype = cfg.compute_dtype if backend == "tpu" else "float32"
    peak = PEAK_FLOPS["tpu" if backend == "tpu" else "cpu"][dtype]
    mfu = _flops_per_round(exp) * rps / peak

    final_acc = exp.logger.last("Test/Acc")
    out = {
        "metric": f"FedDrift SEA-4 round throughput (softcluster, "
                  f"10 clients, M=4, fnn, batch 500)",
        "value": round(rps, 3),
        "unit": "rounds/s",
        "vs_baseline": (round(rps / baseline_rps, 3)
                        if baseline_rps else None),
        "baseline": ({"rounds_per_sec": round(baseline_rps, 3),
                      "what": "same config, this host CPU, per-round "
                              "dispatch path (reference-shaped)"}
                     if baseline_rps else None),
        "final_test_acc": round(float(final_acc), 4),
        "wall_s": round(elapsed, 2),
        "rounds": rounds,
        "backend": backend,
        "probe": probe_diag,
        "mfu_estimate": round(mfu, 6),
        "phases": getattr(exp, "last_phase_summary", None),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
