"""Benchmark: FedDrift canonical config throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Config: the reference's canonical run (README.md:46-50): SEA-4, 10 clients,
fnn, 200 rounds x 5 local steps per time step, batch 500, lr 0.01, 500
samples/client/step. We measure steady-state communication-round throughput
(train_round + the periodic eval), which is the quantity the reference logs
per round ("aggregate time cost", FedAvgEnsAggregatorSoftCluster.py:193-194).

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured, not assumed: before the timed run we execute
the same canonical config on THIS HOST's CPU through the per-round
dispatch path (cfg.chunk_rounds=False — one host->device dispatch and one
eval fetch per round, the closest shape to the reference's per-round
message loop) for a short sample and extrapolate rounds/s.  The reported
ratio is therefore "device fused path vs this host's CPU per-round path";
it is an intra-framework speedup, NOT a measured reference-GPU comparison.
Run with --smoke for a fast CI-sized check.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Peaks now live in the cost model (obs/costmodel.py): a datasheet table
# for TPUs and a MEASURED matmul/stream microbenchmark for CPU hosts, so
# mfu_estimate is non-null on every backend — the numerator comes from
# XLA's cost_analysis of the actual compiled round program, the
# denominator from what this silicon demonstrably does.
from feddrift_tpu.obs.costmodel import PEAK_FLOPS  # noqa: F401  (re-export
# kept: scripts/roofline_report.py and older notebooks read bench.PEAK_FLOPS)


def _probe_backend(attempts: int = 3, timeout_s: float = 120.0):
    """Return (usable backend name, probe diagnosis list).

    The axon TPU tunnel can hang indefinitely at client creation when the
    remote side is unhealthy; a hung benchmark records nothing. Each probe
    runs in a SUBPROCESS (an in-process thread would wedge this process:
    backend creation holds jax's global init lock, so once a thread hangs in
    it no other thread can create any backend). The tunnel also flakes
    transiently, so we retry before falling back. On timeout the main
    process — which has not initialized any backend yet — pins the CPU
    platform; the per-attempt diagnosis is returned for the bench JSON.
    """
    import subprocess

    diagnosis = []
    for i in range(attempts):
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)));"
                 "print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s)
            if out.returncode == 0 and out.stdout.strip():
                backend = out.stdout.strip().splitlines()[-1]
                diagnosis.append(f"attempt {i}: ok ({backend}, "
                                 f"{time.time() - t0:.0f}s)")
                return backend, diagnosis
            diagnosis.append(
                f"attempt {i}: exited {out.returncode}: "
                + (out.stderr or "").strip()[-300:])
        except subprocess.TimeoutExpired:
            diagnosis.append(f"attempt {i}: timed out after {timeout_s:.0f}s")
    jax.config.update("jax_platforms", "cpu")
    print(json.dumps({"warning": "default backend unreachable; "
                      "benchmarking on CPU fallback",
                      "probe": diagnosis}), file=sys.stderr)
    return "cpu-fallback", diagnosis


def _enable_compile_cache() -> None:
    """Shared persistent compile cache (feddrift_tpu/utils/cache.py) —
    kept as a name here because the subprocess baselines invoke it as
    ``bench._enable_compile_cache()``."""
    from feddrift_tpu.utils.cache import enable_compile_cache

    enable_compile_cache()


def _canonical_cfg(smoke: bool, **overrides):
    from feddrift_tpu.config import ExperimentConfig

    base = dict(
        dataset="sea", model="fnn", concept_drift_algo="softcluster",
        concept_drift_algo_arg="H_A_C_1_10_0", concept_num=4,
        change_points="A",
        client_num_in_total=10, client_num_per_round=10,
        train_iterations=3 if smoke else 10,
        comm_round=20 if smoke else 200,
        epochs=5, batch_size=500, sample_num=100 if smoke else 500,
        lr=0.01, frequency_of_the_test=10,
        # honest phase attribution: block on device output inside each
        # traced phase so async dispatch can't bill train time to eval
        trace_sync=True,
        # full XLA memory accounting (obs/costmodel.py): the benchmark is
        # exactly where the extra per-program compile is worth exact
        # peak-HBM numbers (and the persistent compile cache halves it)
        cost_model="compiled",
        report_client=0)
    base.update(overrides)
    return ExperimentConfig(**base)


def _flops_per_example(exp) -> float:
    """Forward FLOPs per example via XLA cost analysis (obs/costmodel.py;
    kept as a bench.* name — scripts call it)."""
    from feddrift_tpu.obs import costmodel

    return costmodel.forward_flops_per_example(exp)


def _flops_per_round(exp) -> float:
    """Analytic round-FLOPs estimate (obs/costmodel.py; the measured path
    prefers the captured round program's own cost — see _measure)."""
    from feddrift_tpu.obs import costmodel

    return costmodel.analytic_round_flops(exp)


def _json_from_subprocess(cmd: list[str], timeout: float, tag: str):
    """Run cmd, return the last JSON line of its stdout, or None — with the
    stderr tail surfaced in the warning so a crash is distinguishable from
    a timeout."""
    import subprocess

    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout,
                             cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in reversed(out.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        print(json.dumps({"warning": f"{tag} produced no JSON",
                          "stderr": (out.stderr or "")[-300:]}),
              file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(json.dumps({"warning": f"{tag} timed out after {timeout:.0f}s"}),
              file=sys.stderr)
    return None


# The two CPU-side baselines are backend-independent and cost tens of
# minutes on this 1-core host; the supervisor reruns bench.py after every
# tunnel flake, so they are cached on disk across invocations.
_BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".bench_baseline_cache.json")


def _code_version() -> str:
    """Content hash of the measured code path (the framework package plus
    this file), so cached baselines are invalidated by any perf-relevant
    change (round-3 advisor: a baseline measured before e.g. a sampler
    restructure must not skew vs_baseline after it) — but survive doc-only
    commits, which on this 1-core host would otherwise re-pay ~35 min."""
    import hashlib

    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    paths = [os.path.join(root, "bench.py")]
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "feddrift_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        paths.extend(os.path.join(dirpath, f)
                     for f in filenames if f.endswith((".py", ".cpp")))
    for p in sorted(paths):
        try:
            with open(p, "rb") as f:
                h.update(os.path.relpath(p, root).encode())
                h.update(f.read())
        except OSError:
            pass
    return h.hexdigest()[:12]


def _baseline_cache(key: str, measure):
    key = f"{key}@{_code_version()}"
    try:
        with open(_BASELINE_CACHE) as f:
            cache = json.load(f)
    except (OSError, json.JSONDecodeError):
        cache = {}
    if key in cache:
        return cache[key]
    val = measure()
    if val is not None:
        cache[key] = val
        # prune entries from older code versions: each is a multi-minute
        # measurement keyed by a hash that will never be looked up again,
        # so without this the cache grows one dead entry per perf-relevant
        # commit (suffix comes from the already-built key — no second
        # package-tree hash walk)
        suffix = "@" + key.rsplit("@", 1)[1]
        cache = {k: v for k, v in cache.items()
                 if k.endswith(suffix) or "@" not in k}
        try:
            with open(_BASELINE_CACHE, "w") as f:
                json.dump(cache, f)
        except OSError:
            pass
    return val


def _measure_cpu_baseline(smoke: bool) -> float | None:
    """Rounds/s of the canonical config on this host's CPU through the
    PER-ROUND dispatch path (chunk_rounds=False) — the measured stand-in
    for the reference's per-round message loop. Runs in a subprocess so the
    main process's backend choice (TPU) is untouched."""
    code = (
        "import jax, json, time;"
        "jax.config.update('jax_platforms', 'cpu');"
        "import bench;"
        "bench._enable_compile_cache();"
        "from feddrift_tpu.simulation.runner import Experiment;"
        f"cfg = bench._canonical_cfg({smoke}, train_iterations=3, "
        "comm_round=20, chunk_rounds=False);"
        "exp = Experiment(cfg);"
        # warm-up t=0 AND t=1: t>=1 is the first trace of the acc_cells /
        # merge path (same reason the main measurement starts at t=2)
        "exp.run_iteration(0); exp.run_iteration(1);"
        "t0 = time.time(); exp.run_iteration(2);"
        "jax.block_until_ready(exp.pool.params);"
        "print(json.dumps({'rps': cfg.comm_round / (time.time() - t0)}))")
    d = _json_from_subprocess([sys.executable, "-c", code], 1200,
                              "cpu baseline")
    return float(d["rps"]) if d and "rps" in d else None


def _measure_with_retry(cfg, backend: str, attempts: int = 2) -> dict:
    """_measure with one in-process retry, returning {"error": ...} on
    exhaustion.

    Scope of the retry: only failures that DON'T kill the backend client
    (trace/shape errors, transient host issues). When the axon tunnel
    itself drops (``UNAVAILABLE: TPU backend setup/compile error``) the
    process's cached PJRT client is dead and every further attempt fails
    identically (the same in-process poisoning _probe_backend documents) —
    for that case main() exits nonzero and scripts/tpu_supervisor.sh
    relaunches the whole benchmark in a fresh process. A 30-minute
    benchmark must never lose every number to one flake (round-3
    incident: the canonical result was computed and then discarded when
    the conv config crashed before the final print).
    """
    last = None
    for i in range(attempts):
        try:
            return _measure(cfg, backend)
        except Exception as e:            # jax errors share no useful base
            last = e
            print(json.dumps({"warning": f"measure attempt {i} failed: "
                              f"{type(e).__name__}: {str(e)[:200]}"}),
                  file=sys.stderr)
    return {"error": f"{type(last).__name__}: {str(last)[:300]}"}


def _measure_reference_shape() -> dict | None:
    """Cross-framework datapoint: the reference's execution shape
    (per-model torch loops, Adam steps, pickled state_dict transport,
    weighted averaging — scripts/reference_shape_bench.py) timed on this
    host's CPU in a subprocess. Complements the intra-framework baseline:
    same canonical config, same silicon, different framework."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "reference_shape_bench.py")
    return _json_from_subprocess([sys.executable, script], 900,
                                 "reference-shape baseline")


def _dispatch_rtt(backend: str) -> dict | None:
    """Per-dispatch round-trip latency of a trivial compiled op. Over the
    axon tunnel every dispatch pays network RTT, which dominates tiny-model
    configs (round-3 weak #1: the 20-round TPU smoke was SLOWER than the
    host CPU's fused path); this number lets the bench artifact say exactly
    how much of a round is tunnel, not device."""
    if not backend.startswith("tpu"):
        return None
    try:
        import jax.numpy as jnp

        f = jax.jit(lambda v: v + 1.0)
        x = jnp.zeros((8,), jnp.float32)
        jax.block_until_ready(f(x))           # compile outside the timing
        ts = []
        for _ in range(30):
            t0 = time.time()
            jax.block_until_ready(f(x))
            ts.append(time.time() - t0)
        ts.sort()
        return {"median_ms": round(1e3 * ts[len(ts) // 2], 3),
                "p90_ms": round(1e3 * ts[int(len(ts) * 0.9)], 3),
                "n": len(ts)}
    except Exception as e:   # diagnostic only: never discard measured results
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}


def _profile_capture(cfg, profile_dir: str) -> str | None:
    """Capture a jax.profiler device trace of the config's fused programs on
    a SHORT replica run (4 time steps, 20 rounds each): the same compiled
    kernels as the headline measurement (compile cache shared), but trace
    collection never pollutes the timed steady state and the canonical
    rounds count keeps its defined scale. Returns the trace dir, or None."""
    from feddrift_tpu.simulation.runner import Experiment

    try:
        from dataclasses import replace
        short = replace(cfg, train_iterations=4, comm_round=20)
        exp = Experiment(short)
        exp.run_iteration(0)                  # warm-up / compile (see _measure)
        exp.run_iteration(1)
        jax.block_until_ready(exp.pool.params)
        jax.profiler.start_trace(profile_dir)
        try:
            exp.run_iteration(2)
            exp.run_iteration(3)
            jax.block_until_ready(exp.pool.params)
        finally:
            jax.profiler.stop_trace()
        return profile_dir
    except Exception as e:                   # profiling is evidence, not gate
        print(json.dumps({"warning": f"profiler capture failed: "
                          f"{type(e).__name__}: {str(e)[:200]}"}),
              file=sys.stderr)
        return None


def _round_wall_quantiles(instruments: dict) -> dict | None:
    """Pull the per-round wall-time quantile digest out of a registry
    snapshot. The runner feeds the ``round_wall_seconds_q`` P² sketch once
    per iteration regardless of the ops plane, so steady-state p50/p95/p99
    are always available here; None before any timed iteration landed."""
    entry = instruments.get("round_wall_seconds_q")
    if not isinstance(entry, dict):
        return None
    q = entry.get("quantiles")
    return {k: round(v, 6) for k, v in q.items() if v is not None} \
        if q else None


def _measure(cfg, backend: str) -> dict:
    """Run one config to steady state and return its measured numbers."""
    from feddrift_tpu import obs
    from feddrift_tpu.obs import costmodel
    from feddrift_tpu.simulation.runner import Experiment

    # Per-measurement program costs: a previous config's captured round
    # program must not feed this config's MFU.
    costmodel.clear()
    exp = Experiment(cfg)

    # Warm-up: run time steps 0 AND 1 fully — t=0 takes the cluster_init
    # branch only; t>=1 is the first to trace acc_cells / the hierarchical
    # merge path, so steady-state timing must start at t=2. The cost model
    # captures each program's XLA accounting at these first compiles.
    exp.run_iteration(0)
    exp.run_iteration(1)

    # Reset instruments AFTER warm-up so the snapshot attached to the
    # result covers exactly the timed steady state: compile counts here
    # mean steady-state retraces (ideally zero), and the phase_seconds
    # histograms are per-phase latency distributions of the measured rounds.
    # The per-program cost gauges were captured during warm-up and are
    # static facts of the compiled programs, so they are re-populated.
    obs.registry().reset()
    costmodel.refresh_gauges()

    # Timed steady state: the remaining time steps. Per-iteration
    # round_breakdown records (runner critical-path accounting) are
    # collected as they are emitted — host_overhead_frac is the gated
    # signal, the full segment stats ride along for attribution.
    breakdowns = []
    t0 = time.time()
    for t in range(2, cfg.train_iterations):
        exp.run_iteration(t)
        if exp.last_round_breakdown is not None:
            breakdowns.append(exp.last_round_breakdown)
    jax.block_until_ready(exp.pool.params)
    elapsed = time.time() - t0
    rounds = cfg.comm_round * (cfg.train_iterations - 2)
    rps = rounds / elapsed

    # MFU from the COST MODEL on every backend: FLOPs/round preferring
    # XLA's cost_analysis of the captured round program (source
    # "cost_analysis"; analytic fallback otherwise), peak from the
    # datasheet on TPU and a measured matmul microbenchmark elsewhere —
    # a real utilization number instead of the historical null.
    # Policy-resolved compute dtype: "auto" keeps the historical rule
    # (cfg.compute_dtype on TPU, f32 elsewhere); an explicit precision
    # preset pins it on every backend (core/precision.py).
    from feddrift_tpu.core.precision import resolve_precision
    effective_dtype = resolve_precision(
        cfg, backend="tpu" if backend.startswith("tpu") else "cpu"
    ).compute_dtype
    flops_round, flops_source = costmodel.round_flops(exp)
    peak, peak_source = costmodel.peak_flops(backend, effective_dtype)
    mfu = round(flops_round * rps / peak, 6)
    roofline = costmodel.roofline(
        flops_round * rounds,
        (costmodel.round_bytes(exp) or 0) * rounds or None,
        elapsed, backend, effective_dtype)

    # Peak HBM: XLA's static memory_analysis of the captured programs
    # (cost_model="compiled") plus the live device watermark where the
    # backend has allocator stats (None on CPU — graceful).
    costmodel.record_hbm_watermark()
    hbm_peak = costmodel.hbm_peak_bytes()

    # Critical-path numbers over the timed iterations: mean host-overhead
    # fraction (the regress ceiling) + dispatch-gap stats. trace_sync=True
    # in the canonical config means every round is dispatch-to-ready
    # profiled, so the fraction is exact, not sampled.
    hofs = [b["host_overhead_frac"] for b in breakdowns]
    gaps = [b["dispatch_gap_s"] for b in breakdowns]
    host_overhead = (round(sum(hofs) / len(hofs), 6) if hofs else None)
    dispatch_gap = ({"mean_s": round(sum(gaps) / len(gaps), 6),
                     "max_s": round(max(gaps), 6),
                     "iterations": len(gaps)} if gaps else None)

    # Streaming tail latency: the runner feeds a P² sketch per timed
    # iteration (obs/quantiles.py), so the steady-state p50/p95/p99 of
    # per-round wall time ride the artifact without sample retention.
    instruments = obs.registry().snapshot()
    wall_q = _round_wall_quantiles(instruments)

    return {
        "value": round(rps, 3),
        "unit": "rounds/s",
        "final_test_acc": round(float(exp.logger.last("Test/Acc")), 4),
        "wall_s": round(elapsed, 2),
        "rounds": rounds,
        "mfu_estimate": mfu,
        "mfu": {"source": flops_source, "flops_per_round": flops_round,
                "peak_flops": peak, "peak_source": peak_source,
                "dtype": effective_dtype},
        "roofline": roofline,
        "hbm_peak_bytes": hbm_peak,
        "host_overhead_frac": host_overhead,
        "dispatch_gap": dispatch_gap,
        "round_wall_p99_s": (wall_q or {}).get("0.99"),
        "round_wall_quantiles": wall_q,
        "round_breakdown": (breakdowns[-1] if breakdowns else None),
        "program_costs": {fn: pc.to_event_fields()
                          for fn, pc in costmodel.costs().items()},
        "phases": getattr(exp, "last_phase_summary", None),
        # Cross-layer instrument snapshot for the steady state: compile /
        # recompile counts per program, phase_seconds histograms, program
        # cost + hbm_peak_bytes gauges, comm counters when a transport is
        # active (obs/instruments.py).
        "instruments": instruments,
    }


def _popscale_cfg(smoke: bool, population: int):
    """Fixed cohort, growing registered population: the population-scale
    participation axis (ISSUE 6). Straggler + churn chaos is ON so the
    measured path is the production-shaped one (masked rounds, registry
    bookkeeping), and the cohort geometry never changes — the whole point
    is that XLA programs are shaped by the cohort, not the population."""
    return _canonical_cfg(
        smoke, population_size=population, cohort_size=10,
        cohort_overprovision=2, straggler_prob=0.1,
        churn_leave_prob=0.01, churn_join_prob=0.02,
        sample_num=50, batch_size=50, train_iterations=4,
        comm_round=10 if smoke else 20,
        cost_model="lowered")     # exact-HBM capture not worth 3 extra compiles here


def _popscale_bench(backend: str, smoke: bool) -> list:
    """rounds/s + steady-state recompile counts vs population size.

    The POPSCALE artifact the `regress` gate checks: throughput must hold
    within the rounds tolerance per population point and steady-state
    recompiles must stay ZERO as the population grows 10^2 -> 10^4."""
    from feddrift_tpu.obs.regress import _compile_counts
    out = []
    for population in (100, 1000) if smoke else (100, 1000, 10000):
        cfg = _popscale_cfg(smoke, population)
        r = _measure_with_retry(cfg, backend)
        _, recompiles = _compile_counts(r)
        out.append({
            "population": population,
            "cohort_slots": cfg.cohort_slots,
            "rounds_per_sec": r.get("value"),
            "final_test_acc": r.get("final_test_acc"),
            "wall_s": r.get("wall_s"),
            "steady_recompiles": recompiles,
            **({"error": r["error"]} if "error" in r else {}),
        })
        print(json.dumps({"partial": f"popscale@{population}", **out[-1]}),
              file=sys.stderr)
    return out


def _instr_value(instruments: dict, name: str, **labels):
    """One series from a registry snapshot; keys are name{k="v"}."""
    if not labels:
        return instruments.get(name)
    key = name + "{" + ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())) + "}"
    return instruments.get(key)


def _hostscale_cfg(smoke: bool, population: int):
    """The popscale geometry with the full host-plane observatory ON
    (sampling profiler + ledger): what we are measuring here is the HOST
    control plane's cost as the registered population grows, with the
    device program held fixed by the cohort shape."""
    cfg = _popscale_cfg(smoke, population)
    import dataclasses
    return dataclasses.replace(cfg, hostprof_hz=50.0)


def _hostscale_bench(backend: str, smoke: bool) -> dict:
    """Per-subsystem host-seconds/round and host-bytes vs population P,
    with fitted log-log scaling exponents (ISSUE 19).

    The HOSTSCALE artifact the `regress` hostscale axis gates: the dense
    registry columns, assign_hist and cohort planning are O(P) by
    construction — this measures their actual exponents and bytes/client
    so the ROADMAP item-2 refactor has named numbers to beat. Seconds
    come from the host_ledger_seconds_total counters, which accumulate
    exactly the steady state because _measure resets the instrument
    registry after warm-up; bytes are the ledger's latest-value gauges."""
    from feddrift_tpu.obs.hostprof import SUBSYSTEMS, fit_scaling
    from feddrift_tpu.obs.regress import _compile_counts
    structures = ("registry_columns", "assign_hist", "routing_table",
                  "staged_shards")
    rows = []
    populations = (100, 1000) if smoke else (100, 1000, 10000, 100000)
    for population in populations:
        cfg = _hostscale_cfg(smoke, population)
        r = _measure_with_retry(cfg, backend)
        _, recompiles = _compile_counts(r)
        instr = r.get("instruments") or {}
        rounds = max(r.get("rounds") or 1, 1)
        sec = {}
        for sub in SUBSYSTEMS:
            total = _instr_value(instr, "host_ledger_seconds_total",
                                 subsystem=sub)
            sec[sub] = (round(total / rounds, 8)
                        if isinstance(total, (int, float)) else None)
        byt = {s: _instr_value(instr, "host_bytes", structure=s)
               for s in structures}
        rows.append({
            "population": population,
            "cohort_slots": cfg.cohort_slots,
            "rounds_per_sec": r.get("value"),
            "wall_s": r.get("wall_s"),
            "steady_recompiles": recompiles,
            "seconds_per_round": sec,
            "bytes": byt,
            "rss_peak_bytes": _instr_value(instr, "host_rss_peak_bytes"),
            **({"error": r["error"]} if "error" in r else {}),
        })
        print(json.dumps({"partial": f"hostscale@{population}",
                          **rows[-1]}), file=sys.stderr)
    pops = [row["population"] for row in rows]
    exp_seconds = {
        sub: fit_scaling(pops, [(row["seconds_per_round"] or {}).get(sub)
                                for row in rows])
        for sub in SUBSYSTEMS}
    exp_bytes = {
        s: fit_scaling(pops, [(row["bytes"] or {}).get(s) for row in rows])
        for s in structures}
    top = rows[-1]
    bytes_per_client = {
        s: round(v / top["population"], 3)
        for s, v in (top["bytes"] or {}).items()
        if isinstance(v, (int, float)) and v > 0}
    return {
        "populations": pops,
        "rows": rows,
        "exp_seconds": {k: round(v, 4) if v is not None else None
                        for k, v in exp_seconds.items()},
        "exp_bytes": {k: round(v, 4) if v is not None else None
                      for k, v in exp_bytes.items()},
        "bytes_per_client": bytes_per_client,
    }


def _hierarchy_bench(smoke: bool) -> list:
    """Broker bytes/round per wire codec (ISSUE 8: verified compression on
    the update path). Backend-independent by design — the codecs are numpy
    on the wire, so the measurement is the negotiated sender/receiver pair
    over the real TCP broker, read off the broker_bytes_out counter (delta,
    not reset: the registry also carries this process's compile counters).

    The COMM artifact the `regress` gate checks: bytes/round per codec must
    not grow past the bytes tolerance, and every lossy codec must keep its
    >= 3x reduction over uncompressed."""
    import numpy as np

    from feddrift_tpu import obs
    from feddrift_tpu.comm.compress import (WIRE_CODECS, UpdateReceiver,
                                            UpdateSender)
    from feddrift_tpu.comm.netbroker import NetworkBroker, NetworkBrokerClient

    rng = np.random.RandomState(8)
    # mnist-fnn-shaped update (784 -> 128 -> 10): ~406 KB of float32 per
    # round — large enough that payload, not JSON framing, is what's timed
    shapes = [(784, 128), (128,), (128, 10), (10,)]
    layers = [rng.randn(*s).astype(np.float32) for s in shapes]
    rounds = 3 if smoke else 10

    def run(codec):
        obs.configure(None)
        ctr = obs.registry().counter("broker_bytes_out", transport="netbroker")
        before = ctr.value
        broker = NetworkBroker()
        try:
            ctx = NetworkBrokerClient(broker.host, broker.port)
            crx = NetworkBrokerClient(broker.host, broker.port)
            rx = UpdateReceiver(crx, "bench/update")
            tx = UpdateSender(ctx, "bench/update", codec=codec)
            for c in (ctx, crx):   # TCP subscribe is async: loopback sync
                q = c.subscribe("__sync__")
                c.publish("__sync__", "ready")
                assert q.get(timeout=10) == "ready"
            tx.offer()
            rx.serve_ctl(timeout=10.0)
            assert tx.wait_accept(timeout=10.0) == codec
            for r in range(rounds):
                for i, base_arr in enumerate(layers):
                    # evolving weights so the delta chain sees realistic
                    # round-over-round updates, not a constant tensor
                    arr = base_arr + 0.01 * r
                    tx.send(f"w{i}", arr)
                    assert rx.recv(timeout=10.0) is not None
            ctx.close(); crx.close()
        finally:
            broker.close()
        return ctr.value - before

    out = []
    raw = None
    for codec in WIRE_CODECS:
        total = run(codec)
        if codec == "none":
            raw = total
        out.append({
            "codec": codec,
            "rounds": rounds,
            "bytes_total": int(total),
            "bytes_per_round": round(total / rounds, 1),
            "ratio_vs_none": (round(raw / total, 2) if raw else None),
        })
        print(json.dumps({"partial": f"hierarchy@{codec}", **out[-1]}),
              file=sys.stderr)
    return out


def _secure_bench(smoke: bool) -> list:
    """Secure-aggregation axis (ISSUE 18): bytes/round + wall overhead vs
    plaintext for both masked round modes (shamir, turbo) at two cohort
    sizes, plus a short real training run per mode proving the secure
    round mode leaves the train program untouched (the share protocol is
    host-side; substitution happens after the device round).

    Per (mode, cohort) row: shamir bytes are measured over the real TCP
    NetworkBroker (share + ack + sum frames of the wire protocol, read
    off the broker_bytes_out counter delta, same idiom as the hierarchy
    axis); turbo has no wire path, so its bytes are static accounting —
    the ring's frame count (C*n contribution shares + (groups-1)*n
    handoffs + T+1 opens) times one actually-encoded frame of the same
    dim.  The plaintext baseline is one quantized frame per client over
    the same transport.  Wall overhead is the in-process engine vs a
    plain numpy sum on identical payloads.

    The SECAGG artifact the `regress` gate checks: bytes_per_round and
    engine wall/round within tolerance per point, and steady-state
    recompiles EXACTLY ZERO on the train rows — secure_agg must never
    mint a new XLA signature."""
    import threading

    import numpy as np

    from feddrift_tpu import obs
    from feddrift_tpu.comm.netbroker import NetworkBroker, NetworkBrokerClient
    from feddrift_tpu.obs.regress import _compile_counts
    from feddrift_tpu.platform.secure_agg import P_DEFAULT, quantize
    from feddrift_tpu.resilience.secure_round import (SecureAggregator,
                                                      SecureShareHolder,
                                                      encode_share_frame,
                                                      run_secure_wire_round)

    dim = 2048 if smoke else 16384
    rounds = 3 if smoke else 5
    scale = 2 ** 16

    def plain_tcp_bytes(pay):
        """One quantized upload frame per client over the real broker."""
        obs.configure(None)
        ctr = obs.registry().counter("broker_bytes_out",
                                     transport="netbroker")
        before = ctr.value
        broker = NetworkBroker()
        try:
            tx = NetworkBrokerClient(broker.host, broker.port, timeout=10.0)
            rx = NetworkBrokerClient(broker.host, broker.port, timeout=10.0)
            q = rx.subscribe("secure-bench/plain")
            s = rx.subscribe("__sync__")
            rx.publish("__sync__", "ready")      # sub-then-pub is ordered
            assert s.get(timeout=10) == "ready"
            for c in range(pay.shape[0]):
                tx.publish("secure-bench/plain", encode_share_frame(
                    quantize(pay[c], scale), sender=c))
            for _ in range(pay.shape[0]):
                assert q.get(timeout=10) is not None
            tx.close(); rx.close()
        finally:
            broker.close()
        return ctr.value - before

    def shamir_tcp_bytes(pay):
        """The full wire protocol (shares, acks, masked sums) over TCP:
        C clients x C holders, holders running in threads on their own
        broker connections."""
        obs.configure(None)
        ctr = obs.registry().counter("broker_bytes_out",
                                     transport="netbroker")
        before = ctr.value
        C = pay.shape[0]
        broker = NetworkBroker()
        try:
            clients = [NetworkBrokerClient(broker.host, broker.port,
                                           timeout=10.0) for _ in range(C)]
            holders = [SecureShareHolder(cli, h)
                       for h, cli in enumerate(clients)]
            for h, cli in enumerate(clients):
                q = cli.subscribe(f"__sync__/{h}")
                cli.publish(f"__sync__/{h}", "ready")
                assert q.get(timeout=10) == "ready"
            threads = [threading.Thread(target=hold.run,
                                        kwargs={"timeout": 60.0},
                                        daemon=True) for hold in holders]
            for t in threads:
                t.start()
            server = NetworkBrokerClient(broker.host, broker.port,
                                         timeout=10.0)
            res = run_secure_wire_round(server, pay, threshold=1,
                                        num_holders=C, deadline=30.0,
                                        scale=scale)
            assert not res.degraded, res.reason
            for t in threads:
                t.join(timeout=10)
            server.close()
            for cli in clients:
                cli.close()
        finally:
            broker.close()
        return ctr.value - before

    def turbo_frame_bytes(engine, C):
        """Static accounting: the ring's frame count times one encoded
        frame (all frames carry the same dim-D field vector)."""
        cfg = engine._ring.cfg
        frame = len(encode_share_frame(
            np.zeros(dim, np.int64), sender=0, holder=0, p=P_DEFAULT))
        n_frames = (C * cfg.group_size
                    + (cfg.num_groups - 1) * cfg.group_size
                    + cfg.privacy_t + 1)
        return n_frames * frame

    out = []
    rng = np.random.RandomState(18)
    for mode in ("shamir", "turbo"):
        for cohort in (4, 8):
            pay = rng.randn(cohort, dim).astype(np.float64)
            eng = SecureAggregator(mode, cohort, threshold=1, scale=scale,
                                   seed=18)
            obs.configure(None)
            t0 = time.time()
            for r in range(rounds):
                res = eng.secure_masked_sum(pay, round_idx=r)
                assert not res.degraded
            wall_sec = (time.time() - t0) / rounds
            t0 = time.time()
            for _ in range(rounds):
                pay.sum(axis=0)
            wall_plain = (time.time() - t0) / rounds
            plain_b = plain_tcp_bytes(pay)
            if mode == "shamir":
                sec_b, transport = shamir_tcp_bytes(pay), "tcp"
            else:
                sec_b, transport = turbo_frame_bytes(eng, cohort), "frames"
            out.append({
                "mode": mode, "point": f"c{cohort}", "cohort": cohort,
                "dim": dim, "rounds": rounds, "transport": transport,
                "bytes_per_round": int(sec_b),
                "plain_bytes_per_round": int(plain_b),
                "bytes_overhead_vs_plain": round(sec_b / plain_b, 2),
                "wall_s_secure_per_round": round(wall_sec, 5),
                "wall_s_plain_per_round": round(wall_plain, 6),
                "wall_overhead_vs_plain": round(
                    wall_sec / max(wall_plain, 1e-9), 1),
                "max_abs_err": res.max_abs_err,
            })
            print(json.dumps({"partial": f"secure@{mode}:c{cohort}",
                              **out[-1]}), file=sys.stderr)
        # Train row: the real runner with secure_agg on — the gate is
        # steady_recompiles == 0 (host-side protocol, untouched program).
        cfg = _canonical_cfg(True, secure_agg=mode, comm_round=5,
                             sample_num=50, batch_size=50,
                             cost_model="lowered")
        r = _measure(cfg, "cpu")
        _, recompiles = _compile_counts(r)
        out.append({
            "mode": mode, "point": "train",
            "rounds_per_sec": r.get("value"),
            "wall_s": r.get("wall_s"),
            "final_test_acc": r.get("final_test_acc"),
            "steady_recompiles": recompiles,
        })
        print(json.dumps({"partial": f"secure@{mode}:train", **out[-1]}),
              file=sys.stderr)
    return out


def _serve_bench(smoke: bool) -> list:
    """Serving read-path axis (ISSUE 14): requests/s + latency quantiles
    across micro-batch buckets over the canonical SEA-4 pool geometry.

    One row per max bucket size. bucket=1 is the unbatched per-request
    path (every dispatch answers one request); larger buckets coalesce the
    same closed-loop traffic through the one routed forward program. The
    SERVE artifact the `regress` gate checks: requests/s floor and p99
    ceiling per bucket, batched >= 3x unbatched, and ZERO steady-state
    recompiles under mixed-cluster traffic (the bucket ladder is compiled
    at warmup; the P2P traffic mix must never mint a new signature)."""
    import numpy as np
    import jax.numpy as jnp

    from feddrift_tpu import obs
    from feddrift_tpu.core.pool import ModelPool
    from feddrift_tpu.data.registry import make_dataset
    from feddrift_tpu.models import create_model
    from feddrift_tpu.platform.serving import (SERVE_BUCKETS,
                                               InferenceEngine,
                                               RoutingTable,
                                               TrafficGenerator)

    cfg = _canonical_cfg(True, train_iterations=1, comm_round=1)
    ds = make_dataset(cfg)
    module = create_model(cfg.model, ds, cfg)
    sample = jnp.asarray(ds.x[0, 0, :2])
    # identical=False: every cluster model answers differently, so routing
    # mistakes would be visible, not silently masked by identical params
    pool = ModelPool.create(module, sample, cfg.num_models,
                            seed=cfg.seed + 42, identical=False)
    population = 64
    rng = np.random.RandomState(14)
    routing = RoutingTable.from_assignment(
        rng.randint(0, cfg.num_models, size=population))
    requests = 600 if smoke else 3000
    concurrency = 32

    def _serve_recompiles() -> int:
        snap = obs.registry().snapshot()
        return sum(int(v) for k, v in snap.items()
                   if k.startswith('jit_recompiles{fn="serve_forward'))

    out = []
    base_rps = None
    for max_bucket in (1, 4, 8, 16, 32):
        buckets = tuple(b for b in SERVE_BUCKETS if b <= max_bucket)
        eng = InferenceEngine(pool, routing, buckets=buckets).start()
        try:
            eng.warmup()
            tg = TrafficGenerator(eng, clients=range(population), seed=14,
                                  concurrency=concurrency)
            tg.run(max(requests // 10, 50))    # closed-loop warm (threads,
            rec0 = _serve_recompiles()         # queues, branch caches)
            eng.reset_latency_stats()          # sketch covers measured
            stats = tg.run(requests)           # traffic only, not warm-up
            recompiles = _serve_recompiles() - rec0
        finally:
            eng.close()
        row = {
            "bucket": max_bucket,
            "mode": "unbatched" if max_bucket == 1 else "batched",
            "requests": stats["requests"],
            "completed": stats["completed"],
            "errors": stats["errors"],
            "concurrency": concurrency,
            "requests_per_s": stats["requests_per_s"],
            "p50_ms": stats.get("p50_ms"),
            "p95_ms": stats.get("p95_ms"),
            "p99_ms": stats.get("p99_ms"),
            "steady_recompiles": int(recompiles),
        }
        if max_bucket == 1:
            base_rps = stats["requests_per_s"]
            row["speedup_vs_unbatched"] = 1.0
        else:
            row["speedup_vs_unbatched"] = (
                round(stats["requests_per_s"] / base_rps, 2)
                if base_rps else None)
        out.append(row)
        print(json.dumps({"partial": f"serve@{max_bucket}", **row}),
              file=sys.stderr)

    # socket path (ISSUE 17): the same pool behind the deployable
    # frontend (platform/frontend.py) — 2 replicas, bounded admission,
    # traffic over real HTTP. Two measurements: a closed-loop row (the
    # gated requests/s floor + p99 ceiling, comparable across runs) and
    # an OPEN-LOOP offered-rate ladder for the saturation knee — the
    # closed loop slows down with a saturated server (coordinated
    # omission), so only the fixed-rate ladder can show where the
    # frontend starts shedding and that sub-knee traffic does NOT shed
    # (the gated shed_rate bound).
    from feddrift_tpu.platform.frontend import (AdmissionController,
                                                FrontendClient,
                                                ServingFrontend,
                                                build_replica_set)
    max_bucket = 8 if smoke else 32
    buckets = tuple(b for b in SERVE_BUCKETS if b <= max_bucket)
    socket_requests = 300 if smoke else 1500
    rs = build_replica_set(pool, routing, n=2, buckets=buckets,
                           max_queue=128)
    fe = ServingFrontend(
        rs, admission=AdmissionController(max_pending=64)).start(port=0)
    try:
        client = FrontendClient(fe.url, timeout=30.0)
        tg = TrafficGenerator(client, clients=range(population), seed=14,
                              concurrency=concurrency)
        tg.run(max(socket_requests // 10, 50))   # warm sockets + threads
        rec0 = _serve_recompiles()
        for eng in rs.engines:
            eng.reset_latency_stats()
        stats = tg.run(socket_requests)
        closed_rps = stats["requests_per_s"]
        # knee ladder: offered rates around the measured closed-loop
        # capacity, with the admit window tightened so overload actually
        # sheds instead of hiding in a worker-pool bound
        fe.admission.max_pending = 32
        open_tg = TrafficGenerator(client, clients=range(population),
                                   seed=15, concurrency=64)
        knee = []

        def _point(rate):
            n = min(socket_requests, max(int(rate * 2), 60))
            o = open_tg.run_open(n, rate, timeout=5.0)
            knee.append({"offered_rps": o["offered_rps"],
                         "achieved_rps": o["achieved_rps"],
                         "shed_rate": o["shed_rate"],
                         "p99_ms": o.get("p99_ms"),
                         "timeouts": o["timeouts"]})
            return knee[-1]

        for frac in (0.5, 1.0, 1.5, 2.0):
            _point(max(closed_rps * frac, 1.0))
        # the closed-loop number is a WORKER-pool bound, not necessarily
        # the server's: if 2x it still neither sheds nor falls behind,
        # keep doubling until the knee is actually visible (sheds, or
        # achieved falls measurably short of offered) so the artifact
        # always contains the saturation point
        rate = closed_rps * 2.0
        for _ in range(6):
            last = knee[-1]
            if (last["shed_rate"] > 0.05
                    or last["achieved_rps"] < 0.85 * last["offered_rps"]):
                break
            rate *= 2.0
            _point(rate)
        recompiles = _serve_recompiles() - rec0
    finally:
        fe.close()
    row = {
        "bucket": max_bucket,
        "mode": "socket",
        "replicas": 2,
        "requests": stats["requests"],
        "completed": stats["completed"],
        "errors": stats["errors"],
        "concurrency": concurrency,
        "requests_per_s": closed_rps,
        "p50_ms": stats.get("p50_ms"),
        "p95_ms": stats.get("p95_ms"),
        "p99_ms": stats.get("p99_ms"),
        # gated bound: the SUB-KNEE (0.5x capacity) open-loop point must
        # serve essentially everything it admits
        "shed_rate": knee[0]["shed_rate"],
        "steady_recompiles": int(recompiles),
        "knee": knee,
    }
    out.append(row)
    print(json.dumps({"partial": f"serve@socket:b{max_bucket}", **row}),
          file=sys.stderr)
    return out


def _quality_bench(smoke: bool) -> dict:
    """Model-quality plane axis (ISSUE 16): seeded drifting-traffic serve
    bench behind QUALITY_r1*.json, gated by `regress` on three absolute
    acceptance bars plus the usual relative throughput/p99 tolerances:

    - the streaming live-accuracy estimate (delayed-label join feeding
      windowed per-model accuracy) lands within --tol-quality-acc of the
      offline oracle computed client-side over the SAME labeled stream;
    - a clean merge (two slots holding bitwise-identical params) canary-
      COMMITS, and a deliberately wrong merge (survivor slot holds an
      anti-model: the classifier layer negated, so re-homed clients get
      flipped logits) canary-ROLLS-BACK — verdict events carry lineage
      ids, and no OTHER canary ever rolls back (clean_canary_rollbacks);
    - shadow duplicate-execution costs < 5% requests/s vs canary-off on
      identical traffic, at ZERO steady-state recompiles (the shadow
      forward replays the warmed bucket signatures).
    """
    import threading

    import numpy as np
    import jax
    import jax.numpy as jnp

    from feddrift_tpu import obs
    from feddrift_tpu.core.pool import ModelPool
    from feddrift_tpu.data.registry import make_dataset
    from feddrift_tpu.models import create_model
    from feddrift_tpu.platform.canary import CanaryController
    from feddrift_tpu.platform.serving import (InferenceEngine, RoutingTable,
                                               TrafficGenerator)

    cfg = _canonical_cfg(True, train_iterations=1, comm_round=1)
    ds = make_dataset(cfg)
    module = create_model(cfg.model, ds, cfg)
    sample = jnp.asarray(ds.x[0, 0, :2])
    pool = ModelPool.create(module, sample, cfg.num_models,
                            seed=cfg.seed + 42, identical=False)
    # slot 1 := slot 0 — two clusters whose models genuinely converged;
    # merging them is the GOOD swap (shadow answers match live bitwise)
    pool.copy_slot(1, 0)
    # slot 2 := slot 3 with the classifier layer negated — a corrupt
    # survivor; merging 3 into 2 is the DELIBERATELY WRONG swap (the
    # candidate generation answers re-homed clients with flipped logits)
    p3 = pool.slot(3)
    last_layer = sorted(p3.keys())[-1]
    pool.set_slot(2, {k: (jax.tree_util.tree_map(lambda a: -a, v)
                          if k == last_layer else v)
                      for k, v in p3.items()})

    population = 64
    rng = np.random.RandomState(14)
    routing = RoutingTable(rng.randint(0, cfg.num_models, size=population))
    window = 200 if smoke else 400
    eps = 0.1               # label noise: live accuracy targets ~0.9
    eng = InferenceEngine(pool, routing, quality_window=window).start()
    ctl = CanaryController(eng, fraction=1.0, min_samples=48,
                           acc_margin=0.02, seed=3, timeout_s=600.0)
    # genesis history so verdict lineage ids resolve through the DAG
    for m in range(cfg.num_models):
        ctl.note_event({"kind": "cluster_create", "model": m,
                        "iteration": 0})
    eng.attach_canary(ctl)

    def _serve_recompiles() -> int:
        snap = obs.registry().snapshot()
        return sum(int(v) for k, v in snap.items()
                   if k.startswith('jit_recompiles{fn="serve_forward'))

    num_classes = int(np.asarray(eng.step.forward(
        eng._gen.params,
        jnp.zeros((1,) + eng._example_shape, dtype=eng._example_dtype),
        jnp.zeros((1,), dtype=jnp.int32))).shape[-1])

    lock = threading.Lock()
    oracle: list = []        # (model, correct) from the client's own view

    def labeled_run(n: int, seed: int, concurrency: int = 8,
                    record: bool = False) -> None:
        """Closed-loop labeled traffic: submit, then close the delayed-
        label loop with y = served prediction flipped with prob eps —
        the client-side (pred == y) log IS the offline oracle."""
        per = [n // concurrency] * concurrency
        for i in range(n % concurrency):
            per[i] += 1

        def worker(w: int) -> None:
            wr = np.random.RandomState(
                (seed * 1_000_003 + w * 7_919 + 1) % (2**31 - 1))
            recs = []
            for _ in range(per[w]):
                c = int(wr.randint(population))
                x = wr.standard_normal(eng._example_shape).astype(
                    eng._example_dtype, copy=False)
                try:
                    res = eng.submit(c, x, timeout=30.0)
                except Exception:   # noqa: BLE001 — keep the loop closed
                    continue
                pred = int(np.argmax(res.logits))
                y = pred if wr.uniform() >= eps else \
                    int((pred + 1 + wr.randint(num_classes - 1))
                        % num_classes)
                eng.observe_label(res.request_id, y)
                recs.append((int(res.model), pred == y))
            if record:
                with lock:
                    oracle.extend(recs)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    try:
        eng.warmup()
        TrafficGenerator(eng, clients=range(population), seed=14,
                         concurrency=8).run(100)    # unlabeled warm
        rec0 = _serve_recompiles()

        # phase A — clean labeled traffic: streaming estimate vs oracle
        n_a = window * 2
        labeled_run(n_a, seed=21, record=True)
        snap_a = eng.quality.snapshot()
        per_model = {}
        gaps = [0.0]
        by_model: dict = {}
        for m, ok in oracle:
            by_model.setdefault(m, []).append(ok)
        for m, oks in sorted(by_model.items()):
            # oracle over the estimator's own window, not all history —
            # both sides then summarize the same tail of the stream
            tail = oks[-window:]
            o = float(np.mean(tail))
            lw = (snap_a.get("per_model") or {}).get(str(m)) or {}
            live = lw.get("accuracy")
            row = {"oracle_accuracy": round(o, 4),
                   "live_accuracy": live, "labeled": len(oks)}
            if live is not None and len(tail) >= 30:
                row["gap"] = round(abs(live - o), 4)
                gaps.append(row["gap"])
            per_model[str(m)] = row
        oracle_acc = float(np.mean([ok for _, ok in oracle][-window:]))
        live_acc = snap_a.get("accuracy")
        if live_acc is not None:
            gaps.append(abs(live_acc - oracle_acc))
        print(json.dumps({"partial": "quality@clean",
                          "live_accuracy": live_acc,
                          "oracle_accuracy": round(oracle_acc, 4)}),
              file=sys.stderr)

        # phase B — drifting traffic: shift the input distribution so the
        # read-path entropy stream moves (KS detector; not gated)
        def shifted_x(r):
            return (6.0 * r.standard_normal(eng._example_shape)
                    + 4.0).astype(eng._example_dtype, copy=False)
        TrafficGenerator(eng, clients=range(population), seed=15,
                         concurrency=8,
                         make_x=shifted_x).run(300 if smoke else 600)
        drift_suspected = int(eng.quality.snapshot()["drift_suspected"])

        # phase C — canaried swaps: clean merge commits, corrupt merge
        # rolls back (labels keep flowing so both verdicts close on
        # samples, not timeout)
        def run_canary(rec: dict) -> dict:
            n_before = len(ctl.verdicts)
            eng.apply_cluster_event(rec)
            for i in range(40):
                if len(ctl.verdicts) > n_before:
                    break
                labeled_run(64, seed=1000 + 37 * i)
            if len(ctl.verdicts) == n_before:
                return {"verdict": "hung"}
            v = ctl.verdicts[-1]
            print(json.dumps({"partial": f"quality@{rec['kind']}"
                                         f":{rec.get('merged')}",
                              **{k: v[k] for k in ("verdict", "decided_by",
                                                   "live_acc", "shadow_acc",
                                                   "lineage_ids")}}),
                  file=sys.stderr)
            return v

        good = run_canary({"kind": "cluster_merge", "base": 0, "merged": 1,
                           "iteration": 1})
        bad = run_canary({"kind": "cluster_merge", "base": 2, "merged": 3,
                          "iteration": 2})
        clean_rollbacks = sum(
            1 for v in ctl.verdicts
            if v.get("verdict") == "rollback" and v is not bad)

        # phase D — shadow overhead on identical traffic: INTERLEAVED
        # canary-off/on legs. A single off/on pair is hostage to closed-
        # loop throughput drift on a shared host (observed swings ~±10%
        # dwarf the <5% signal); alternating the modes and comparing
        # medians cancels the monotone warm-up/scheduler component.
        n_perf = 1500 if smoke else 3000
        pairs = 3
        eng.reset_latency_stats()

        def _leg(seed: int, canary_on: bool) -> dict:
            if canary_on:
                ctl.fraction = 0.1
                eng.apply_cluster_event(
                    {"kind": "cluster_merge", "base": 2, "merged": 3,
                     "iteration": 100 + seed})
            r = TrafficGenerator(eng, clients=range(population),
                                 seed=seed, concurrency=32).run(n_perf)
            if canary_on:
                ctl.abort()   # no labels flow here: cancel, next leg is
            return r          # truly canary-idle

        _leg(15, False)       # unmeasured: warm BOTH modes before any
        _leg(15, True)        # measured leg (first-open canary setup —
        off_legs, on_legs = [], []  # lineage replay etc — is one-time)
        for k in range(pairs):
            # alternate which mode goes first: closed-loop throughput
            # drifts monotonically as the host warms, so a fixed order
            # would systematically favor one mode
            modes = (True, False) if k % 2 else (False, True)
            for canary_on in modes:
                r = _leg(16 + 2 * k + int(canary_on), canary_on)
                (on_legs if canary_on else off_legs).append(r)
        recompiles = _serve_recompiles() - rec0
    finally:
        eng.close()

    off_rps = [r["requests_per_s"] for r in off_legs]
    on_rps = [r["requests_per_s"] for r in on_legs]
    off = {"requests_per_s": float(np.median(off_rps)),
           "p99_ms": float(np.median(
               [r["p99_ms"] for r in off_legs if r.get("p99_ms")])),
           "errors": sum(int(r["errors"]) for r in off_legs)}
    on = {"requests_per_s": float(np.median(on_rps)),
          "errors": sum(int(r["errors"]) for r in on_legs)}
    ratio = (round(on["requests_per_s"] / off["requests_per_s"], 4)
             if off["requests_per_s"] else None)
    max_gap = round(max(gaps), 4)
    row = {
        "variant": "drifting_serve",
        "population": population,
        "num_models": cfg.num_models,
        "window": window,
        "label_noise": eps,
        "labeled": int(snap_a["labeled"]),
        "live_accuracy": live_acc,
        "oracle_accuracy": round(oracle_acc, 4),
        "live_oracle_gap": max_gap,
        "per_model": per_model,
        "drift_suspected": drift_suspected,
        "good_merge": {k: good.get(k) for k in
                       ("verdict", "decided_by", "samples", "live_acc",
                        "shadow_acc", "acc_delta", "agreement",
                        "lineage_ids")},
        "bad_merge": {k: bad.get(k) for k in
                      ("verdict", "decided_by", "samples", "live_acc",
                       "shadow_acc", "acc_delta", "agreement",
                       "lineage_ids")},
        "good_merge_committed": int(good.get("verdict") == "commit"),
        "bad_merge_rolled_back": int(bad.get("verdict") == "rollback"),
        "clean_canary_rollbacks": int(clean_rollbacks),
        "shadow_overhead": {"requests": n_perf, "concurrency": 32,
                            "fraction": 0.1, "pairs": pairs,
                            "off_rps": [round(v, 1) for v in off_rps],
                            "on_rps": [round(v, 1) for v in on_rps]},
        "shadow_overhead_ratio": ratio,
        "requests_per_s": round(off["requests_per_s"], 2),
        "p99_ms": round(off["p99_ms"], 3) if off.get("p99_ms") else None,
        "errors": int(off["errors"]) + int(on["errors"]),
        "steady_recompiles": int(recompiles),
    }
    print(json.dumps({"partial": "quality", **row}), file=sys.stderr)
    return row


def _megastep_cfg(smoke: bool, K: int):
    """Megastep K-sweep config: the canonical SEA geometry under the
    drift-OBLIVIOUS single model, which certifies an unbounded
    megastep_horizon — the canonical softcluster decides drift every
    iteration (decision_cadence=1) and would clamp every block to K=1,
    measuring nothing. 16 iterations divide evenly by every swept K, so
    no run ever compiles a second (tail-sized) megastep program."""
    return _canonical_cfg(
        smoke, concept_drift_algo="oblivious", concept_drift_algo_arg="",
        concept_num=1, megastep_k=K,
        train_iterations=16, comm_round=10 if smoke else 20,
        sample_num=50, batch_size=50,
        cost_model="lowered")     # exact-HBM capture not worth the compiles here


def _megastep_pop_cfg(smoke: bool, K: int):
    """Composed megastep geometry: 10^4 registered population (10^3 under
    --smoke), 10-client cohorts with 2 overprovision slots, a 3-edge
    hierarchy closing every round with trimmed-mean, plus straggler/churn
    chaos — the ISSUE-13 acceptance config. Device shapes stay cohort-
    sized; only the host-side plan (registry draw, cohort gather, mask
    stacking) sees the population, which is exactly the overhead the
    K-deep block is meant to amortize.

    Short rounds (comm_round=3) on purpose: the megastep amortizes the
    PER-ITERATION host round-trip (dispatch, opt-state init, phase
    syncs, eval fetches), so the sweep runs the cross-silo-style
    few-local-rounds regime where that round-trip dominates — at long
    R the in-program training compute swamps both paths equally and
    the axis measures nothing. Many short iterations (48 full / 16
    smoke, both divisible by every swept K) keep the steady-state
    sample large without a tail-sized second program."""
    return _canonical_cfg(
        smoke, concept_drift_algo="oblivious", concept_drift_algo_arg="",
        concept_num=1, megastep_k=K,
        population_size=1000 if smoke else 10000,
        cohort_size=10, cohort_overprovision=2,
        straggler_prob=0.1, churn_leave_prob=0.01, churn_join_prob=0.02,
        hierarchy_edges=3, edge_robust_agg="trimmed_mean",
        train_iterations=16 if smoke else 48, comm_round=3,
        sample_num=50, batch_size=50,
        cost_model="lowered")


def _drive_megastep(exp, t: int) -> int:
    """Advance one block through the runner's greedy fusion loop
    (run_iteration never fuses; run_megastep fuses the granted span)."""
    span = exp._megastep_span(t)
    if span > 1:
        return t + exp.run_megastep(t, span)
    exp.run_iteration(t)
    return t + 1


def _measure_megastep_sweep(cfgs, backend: str) -> list:
    """Measure all K points of one megastep variant INTERLEAVED.

    The K sweep's headline number is a RATIO (K>1 rounds/s over the same
    variant's K=1), so the two measurements must see the same host: on a
    small shared box, minutes of load drift between sequentially-measured
    points swings either side of the ratio by 30% — more than the effect
    under test. Countermeasures, in order of leverage:

      - interleave: every experiment is constructed and warmed up front,
        then the steady state advances round-robin in equal-iteration
        turns (max swept K per turn), so a load burst hits every K point
        instead of whichever one was running;
      - MIN per-iteration wall over turns, not total elapsed: steady
        turns are identical work and scheduler noise is strictly
        additive, so the fastest turn is the tightest upper bound on
        the true cost (same paired-min reasoning as perf_gate's ops
        stage; the total stays in wall_s).

    Warm-up is each experiment's first block (first two iterations when
    K=1, matching _measure); the instruments registry resets after ALL
    warm-ups, so the shared snapshot counts steady-state retraces across
    the sweep — every row must show ZERO, and a nonzero count correctly
    poisons the whole variant."""
    from feddrift_tpu import obs
    from feddrift_tpu.obs import costmodel
    from feddrift_tpu.simulation.runner import Experiment

    costmodel.clear()
    exps = [Experiment(c) for c in cfgs]
    ts = []
    for exp, c in zip(exps, cfgs):
        t = 0
        while t < max(c.megastep_k, 2):        # warm-up: first block
            t = _drive_megastep(exp, t)
        ts.append(t)
    obs.registry().reset()
    costmodel.refresh_gauges()
    starts = list(ts)
    chunk = max(c.megastep_k for c in cfgs)
    walls = [[] for _ in exps]                 # per-turn (iters, seconds)
    hofs = [[] for _ in exps]
    elapsed = [0.0 for _ in exps]
    while any(t < c.train_iterations for t, c in zip(ts, cfgs)):
        for i, (exp, c) in enumerate(zip(exps, cfgs)):
            target = min(ts[i] + chunk, c.train_iterations)
            if ts[i] >= target:
                continue
            n0 = ts[i]
            b0 = time.perf_counter()
            while ts[i] < target:
                ts[i] = _drive_megastep(exp, ts[i])
                if exp.last_round_breakdown is not None:
                    hofs[i].append(
                        exp.last_round_breakdown["host_overhead_frac"])
            jax.block_until_ready(exp.pool.params)
            dt = time.perf_counter() - b0
            walls[i].append((ts[i] - n0, dt))
            elapsed[i] += dt
    instruments = obs.registry().snapshot()
    out = []
    for i, (exp, c) in enumerate(zip(exps, cfgs)):
        per_iter = sorted(w / max(n, 1) for n, w in walls[i])
        best = per_iter[0] if per_iter else None
        rounds = c.comm_round * (c.train_iterations - starts[i])
        rps = (c.comm_round / best) if best \
            else rounds / max(elapsed[i], 1e-9)
        out.append({
            "value": round(rps, 3),
            "unit": "rounds/s",
            "wall_s": round(elapsed[i], 2),
            "rounds": rounds,
            "final_test_acc": round(float(exp.logger.last("Test/Acc")), 4),
            "host_overhead_frac": (round(sum(hofs[i]) / len(hofs[i]), 6)
                                   if hofs[i] else None),
            "round_wall_p99_s": (_round_wall_quantiles(instruments)
                                 or {}).get("0.99"),
            "instruments": instruments,
        })
    return out


def _measure_megastep(cfg, backend: str) -> dict:
    """Single-config megastep measurement (the sweep of one)."""
    return _measure_megastep_sweep([cfg], backend)[0]


def _megastep_bench(backend: str, smoke: bool) -> list:
    """rounds/s + host-overhead fraction + steady-state recompiles vs the
    fused-iterations-per-dispatch factor K, over TWO variants:

    - ``dense`` (K in 1,2,4,8): the PR-10 canonical all-clients-resident
      geometry — K=1 is the historical fused-iteration path;
    - ``pop_hier`` (K in 1,4): the ISSUE-13 composed geometry — 10^4
      population cohorts + 3-edge trimmed-mean hierarchy + chaos, where
      every previously-gating feature now rides the outer scan.

    The MEGASTEP artifact the `regress` gate checks: per-K throughput must
    hold within the rounds tolerance, steady-state recompiles must stay
    ZERO across K and both variants, K>1 must keep host_overhead_frac
    strictly below its own variant's K=1, and the composed pop_hier K>1
    must clear an ABSOLUTE >= 2x speedup over its own K=1 — the
    acceptance bar for fusing the feature matrix, not just the dense
    fast path.

    pop_hier holds an absolute RATIO floor on a 1-core shared host, so
    its sweep runs 3 times and the rep with the MEDIAN K-max/K-1 ratio
    is reported whole (pairing preserved: both sides of the ratio come
    from the same interleaved rep). The zero-recompile gate stays
    absolute across ALL reps — a recompile in a discarded rep still
    poisons the row."""
    from feddrift_tpu.obs.regress import _compile_counts

    out = []
    sweeps = [("dense", _megastep_cfg, (1, 2, 4, 8), 1),
              ("pop_hier", _megastep_pop_cfg, (1, 4), 3)]
    for variant, mk_cfg, ks, reps in sweeps:
        try:
            rep_results = [
                _measure_megastep_sweep([mk_cfg(smoke, K) for K in ks],
                                        backend)
                for _ in range(reps)]
        except Exception as e:        # jax errors share no useful base
            rep_results = [[{"error": f"{type(e).__name__}: {str(e)[:300]}"}
                            for _ in ks]]
        def _ratio(rr):
            v0, vn = rr[0].get("value"), rr[-1].get("value")
            return (vn / v0) if v0 and vn else 0.0
        rep_results.sort(key=_ratio)
        results = rep_results[len(rep_results) // 2]
        k1_rps = None
        for i, (K, r) in enumerate(zip(ks, results)):
            recompiles = max(_compile_counts(rr[i])[1]
                             for rr in rep_results)
            entry = {
                "variant": variant,
                "megastep_k": K,
                "rounds_per_sec": r.get("value"),
                "final_test_acc": r.get("final_test_acc"),
                "wall_s": r.get("wall_s"),
                "host_overhead_frac": r.get("host_overhead_frac"),
                "steady_recompiles": recompiles,
                **({"error": r["error"]} if "error" in r else {}),
            }
            if K == 1:
                k1_rps = entry["rounds_per_sec"]
            entry["speedup_vs_k1"] = (
                round(entry["rounds_per_sec"] / k1_rps, 3)
                if k1_rps and entry["rounds_per_sec"] else None)
            out.append(entry)
            print(json.dumps({"partial": f"megastep@{variant}:{K}",
                              **entry}),
                  file=sys.stderr)
    return out


def _precision_cfg(smoke: bool, policy: str):
    """Compute-bound real-workload preset for the precision axis:
    resnet8 on FMoW-shaped synthetic satellite images (data/fmow.py,
    32x32x3) — the first runnable bench preset pairing the two; the
    canonical fnn is ~21k params, so its precision deltas are noise by
    construction. Drift-oblivious single model: the axis measures the
    round program's dtype economics, not cluster dynamics. Geometry is
    sized so one local step per (client, round) keeps the CPU-emulated
    bf16 sweep affordable while the conv tower still dominates bytes."""
    return _canonical_cfg(
        smoke, dataset="fmow", model="resnet8",
        concept_drift_algo="oblivious", concept_drift_algo_arg="",
        concept_num=1, change_points="A", precision=policy,
        client_num_in_total=4, client_num_per_round=4,
        epochs=1, batch_size=32, sample_num=32,
        train_iterations=4, comm_round=3 if smoke else 10,
        frequency_of_the_test=3 if smoke else 10,
        cost_model="compiled")    # exact per-program HBM is the point here


def _precision_bench(backend: str, smoke: bool) -> list:
    """End-to-end precision-policy axis (ISSUE 15): the f32 / bf16_mixed /
    bf16_pure presets over the compute-bound resnet8-on-FMoW preset.

    The PRECISION artifact the `regress` gate checks: rounds/s floor per
    policy, every reduced-precision row's accuracy within
    --tol-precision-acc of the same artifact's OWN f32 row, ZERO
    steady-state recompiles (a policy is one jit signature per program,
    compiled in warm-up), and ABSOLUTE ceilings on the bf16_mixed ratios
    — program_bytes_accessed <= 0.60x and wire bytes/round <= 0.55x of
    the paired f32 row. On CPU the bf16 arithmetic is emulated, so
    rounds/s is NOT the portable signal; the bytes ratios are (XLA's
    accounting of the same programs), and the MXU-rate prediction lives
    in TPU_BOTTLENECK.md as a falsifiability row.

    Wire bytes go through the real frame encoder at each policy's wire
    dtype ("none" codec on purpose: the codec axis is COMM's; this axis
    isolates the dtype width, headers included)."""
    import jax.numpy as jnp
    import ml_dtypes
    import numpy as np

    from feddrift_tpu.comm.compress import encode_frame
    from feddrift_tpu.core.precision import PRESETS
    from feddrift_tpu.data.registry import make_dataset
    from feddrift_tpu.models import create_model
    from feddrift_tpu.obs.regress import _compile_counts

    cfg0 = _precision_cfg(smoke, "f32")
    ds = make_dataset(cfg0)
    module = create_model(cfg0.model, ds, cfg0)
    leaves = jax.tree_util.tree_leaves(
        module.init(jax.random.PRNGKey(0),
                    jnp.asarray(ds.x[0, 0, :2]))["params"])
    wire_np = {"float32": np.float32, "bfloat16": ml_dtypes.bfloat16}

    def wire_bytes_per_round(policy: str) -> int:
        dt = wire_np[PRESETS[policy].wire_dtype]
        one_update = sum(
            len(json.dumps(encode_frame(np.asarray(l).astype(dt), "none",
                                        name=f"p{i}")))
            for i, l in enumerate(leaves))
        return one_update * cfg0.client_num_per_round

    out = []
    f32_row = None
    for policy in ("f32", "bf16_mixed", "bf16_pure"):
        cfg = _precision_cfg(smoke, policy)
        r = _measure_with_retry(cfg, backend)
        _, recompiles = _compile_counts(r)
        costs = r.get("program_costs") or {}
        # Pre-optimization accounting: buffers at the widths the program
        # declares. The optimized-HLO bytes_accessed is backend-specialized
        # — XLA:CPU emulates bf16 math in f32 with convert traffic, which
        # would report a bf16 program as COSTLIER than f32 (measured 1.25x
        # on this preset) purely as an emulation artifact.
        bytes_accessed = sum(c.get("lowered_bytes_accessed")
                             or c.get("bytes_accessed") or 0
                             for c in costs.values()) or None
        pol = PRESETS[policy]
        entry = {
            "variant": "resnet",
            "policy": policy,
            "param_dtype": pol.param_dtype,
            "agg_dtype": pol.agg_dtype,
            "wire_dtype": pol.wire_dtype,
            "rounds_per_sec": r.get("value"),
            "final_test_acc": r.get("final_test_acc"),
            "wall_s": r.get("wall_s"),
            "steady_recompiles": recompiles,
            "program_bytes_accessed": bytes_accessed,
            "peak_hbm_bytes": r.get("hbm_peak_bytes"),
            "wire_bytes_per_round": wire_bytes_per_round(policy),
            **({"error": r["error"]} if "error" in r else {}),
        }
        if policy == "f32":
            f32_row = entry
        elif f32_row is not None:
            def _ratio(key):
                a, b = entry.get(key), f32_row.get(key)
                return round(a / b, 4) if a and b else None
            entry["bytes_accessed_ratio"] = _ratio("program_bytes_accessed")
            entry["peak_hbm_ratio"] = _ratio("peak_hbm_bytes")
            entry["wire_bytes_ratio"] = _ratio("wire_bytes_per_round")
        out.append(entry)
        print(json.dumps({"partial": f"precision@{policy}", **entry}),
              file=sys.stderr)
    return out


def _conv_cfg(smoke: bool, **overrides):
    base = dict(
        dataset="cifar10", model="resnet8",
        concept_drift_algo="win-1", concept_drift_algo_arg="",
        concept_num=1, change_points="A",
        batch_size=128, compute_dtype="bfloat16",
        train_iterations=3 if smoke else 4,
        comm_round=10 if smoke else 50)
    base.update(overrides)                    # callers may override any of it
    return _canonical_cfg(smoke, **base)


def _mfu_batch_sweep(backend: str) -> list | None:
    """MFU vs per-client batch size on the conv config (round-3 verdict
    item 3: 'sweep batch size ... and report MFU vs batch in the bench
    output'). The fused round program vmaps C=10 clients, so device batch
    is 10x the per-client figure. Short runs: the sweep wants the MFU
    trend, not steady-state wall-clock (the headline conv_bench covers
    that). Never reached under --smoke (gated at the call site). Same
    predicate as _dispatch_rtt so a qualified backend string ("tpu:v4")
    can't make the two TPU-only diagnostics disagree."""
    if not backend.startswith("tpu"):
        return None
    out = []
    for bs in (128, 256, 512, 1024):
        cfg = _conv_cfg(False, batch_size=bs, train_iterations=3,
                        comm_round=20)
        r = _measure_with_retry(cfg, backend)
        out.append({"batch_per_client": bs, "device_batch": bs * 10,
                    "rounds_per_sec": r.get("value"),
                    "mfu": r.get("mfu_estimate"),
                    **({"error": r["error"]} if "error" in r else {})})
        print(json.dumps({"partial": f"mfu_sweep@{bs}", **out[-1]}),
              file=sys.stderr)
    return out


def main() -> None:
    smoke = "--smoke" in sys.argv
    if "--cpu" in sys.argv:       # explicit local run: skip the probe wait
        jax.config.update("jax_platforms", "cpu")
        backend, probe_diag = "cpu-forced", ["--cpu flag"]
    else:
        backend, probe_diag = _probe_backend()
    _enable_compile_cache()

    # Measured baselines (see module docstring). Skipped under --smoke (the
    # CI-sized check must stay fast; vs_baseline is reported null there).
    # Disk-cached: supervisor retries after a tunnel flake must not re-pay
    # ~35 min of backend-independent single-core work.
    baseline_rps = None if smoke else _baseline_cache(
        "cpu_per_round_rps", lambda: _measure_cpu_baseline(smoke))
    ref_shape = None if smoke else _baseline_cache(
        "torch_reference_shape", _measure_reference_shape)

    baseline_obj = ({"rounds_per_sec": round(baseline_rps, 3),
                     "what": "same config, this host CPU, per-round "
                             "dispatch path (reference-shaped)"}
                    if baseline_rps else None)

    # Optional profiler capture (supervisor sets FEDDRIFT_PROFILE_DIR on
    # real-TPU runs): device-time traces for the canonical + conv configs,
    # captured on short replica runs after the timed measurements.
    prof_root = os.environ.get("FEDDRIFT_PROFILE_DIR") or None

    res = _measure_with_retry(_canonical_cfg(smoke), backend)
    if "error" in res:
        # Report what WAS measured (the baseline took minutes), then exit
        # nonzero so the supervisor retries in a fresh process instead of
        # capturing a null benchmark as final.
        print(json.dumps({"metric": "FedDrift SEA-4 round throughput",
                          "value": None, "unit": "rounds/s",
                          "vs_baseline": None, "baseline": baseline_obj,
                          "backend": backend, "probe": probe_diag, **res}))
        sys.exit(1)
    # Persist the headline result immediately: a later config's tunnel
    # flake must not cost the already-measured number.
    print(json.dumps({"partial": "canonical", **res}), file=sys.stderr)
    res["profile"] = (_profile_capture(_canonical_cfg(smoke),
                                       os.path.join(prof_root, "canonical"))
                      if prof_root else None)

    # Second datapoint on real TPU hardware (or under --conv for local
    # checks): a bf16 conv config where the MXU actually has work — the
    # canonical fnn is ~21k params, so its MFU is noise by construction.
    conv = None
    if backend == "tpu" or "--conv" in sys.argv:
        conv = {"metric": "cifar10 resnet8 bf16 round throughput "
                          "(win-1, 10 clients, batch 128)",
                **_measure_with_retry(_conv_cfg(smoke), backend)}
        if prof_root and "error" not in conv:
            conv["profile"] = _profile_capture(
                _conv_cfg(smoke), os.path.join(prof_root, "conv"))

    out = {
        "metric": "FedDrift SEA-4 round throughput (softcluster, "
                  "10 clients, M=4, fnn, batch 500)",
        **res,
        "vs_baseline": (round(res["value"] / baseline_rps, 3)
                        if baseline_rps else None),
        "baseline": baseline_obj,
        "baseline_torch_reference_shape": ref_shape,
        "vs_torch_reference_shape": (
            round(res["value"] / ref_shape["rounds_per_sec"], 3)
            if ref_shape and ref_shape.get("rounds_per_sec") else None),
        "backend": backend,
        "probe": probe_diag,
        "dispatch_rtt": _dispatch_rtt(backend),
        "conv_bench": conv,
        "mfu_vs_batch": None if smoke else _mfu_batch_sweep(backend),
        # population-scaling axis (opt-in: adds ~5 short population-mode
        # runs); committed as POPSCALE_r0*.json and gated by `regress`
        "popscale": (_popscale_bench(backend, smoke)
                     if "--popscale" in sys.argv else None),
        # host-plane scaling axis (opt-in: population sweep with the
        # sampling profiler + subsystem ledger on, per-subsystem log-log
        # exponents of host-seconds/round and bytes vs P); committed as
        # HOSTSCALE_r1*.json and gated by `regress` (exponent ceilings,
        # bytes/client ceilings, rounds/s floor, zero steady recompiles)
        "hostscale": (_hostscale_bench(backend, smoke)
                      if "--hostscale" in sys.argv else None),
        # two-tier wire axis (opt-in: pure-wire TCP broker measurement);
        # committed as COMM_r0*.json and gated by `regress`
        "hierarchy": (_hierarchy_bench(smoke)
                      if "--hierarchy" in sys.argv else None),
        # multi-iteration megastep axis (opt-in: K-sweep of fused
        # iteration blocks); committed as MEGASTEP_r1*.json and gated by
        # `regress` (rounds/s floor, zero steady recompiles, host
        # overhead strictly below K=1)
        "megastep": (_megastep_bench(backend, smoke)
                     if "--megastep" in sys.argv else None),
        # end-to-end precision-policy axis (opt-in: paired f32 /
        # bf16_mixed / bf16_pure sweep on the resnet8-on-FMoW preset);
        # committed as PRECISION_r1*.json and gated by `regress`
        # (rounds/s floor, accuracy vs own f32 row, zero steady
        # recompiles, bytes_accessed <= 0.60x and wire <= 0.55x absolute)
        "precision": (_precision_bench(backend, smoke)
                      if "--precision" in sys.argv else None),
        # serving read-path axis (opt-in: closed-loop inference over the
        # model pool across micro-batch buckets); committed as
        # SERVE_r1*.json and gated by `regress` (requests/s floor, p99
        # ceiling, batched >= 3x unbatched, zero steady recompiles)
        "serve": (_serve_bench(smoke)
                  if "--serve" in sys.argv else None),
        # secure-aggregation axis (opt-in: masked round modes vs
        # plaintext — wire bytes over the real TCP broker + engine wall
        # overhead at 2 cohort sizes, and a train run per mode);
        # committed as SECAGG_r1*.json and gated by `regress`
        # (bytes/wall tolerance per point, zero steady recompiles)
        "secure": (_secure_bench(smoke)
                   if "--secure" in sys.argv else None),
        # model-quality plane axis (opt-in: labeled drifting-traffic
        # serve bench with canaried swaps); committed as QUALITY_r1*.json
        # and gated by `regress` (live-vs-oracle accuracy gap, canary
        # verdicts, shadow overhead < 5%, zero steady recompiles)
        "quality": (_quality_bench(smoke)
                    if "--quality" in sys.argv else None),
    }
    print(json.dumps(out))
    if conv is not None and "error" in conv:
        sys.exit(1)   # partial result: let the supervisor retry for both


if __name__ == "__main__":
    main()
