"""Plugging a custom dataset AND model into the drift pipeline.

The reference hardwires its drift pipeline to five datasets and eight model
names through closed switches (fedavg_cont_ens/main_fedavg.py:145-224); adding
one of your own means editing the framework. Here both registries are open —
this example registers:

- ``xor-rot``: a synthetic drifting dataset whose concept rotates the decision
  boundary of a 2-D XOR problem (concept k = boundary rotated by k * 30 deg),
  driven by the SAME change-point machinery as the built-ins, and
- ``tiny-mlp``: a custom flax model,

then runs FedDrift (softcluster) on them, unchanged. Run:

    python examples/custom_plugin.py
"""

from __future__ import annotations

import os
import sys

import flax.linen as nn
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data.drift_dataset import DriftDataset
from feddrift_tpu.data.registry import register_dataset
from feddrift_tpu.models import register_model


# ---------------------------------------------------------------------------
# 1. A custom drifting dataset.
@register_dataset("xor-rot")
def make_xor_rot(cfg: ExperimentConfig, change_points: np.ndarray) -> DriftDataset:
    """XOR with a per-concept rotated boundary.

    ``change_points`` is the [T, C] concept-id matrix the framework resolved
    from cfg.change_points (a preset letter or 'rand') — custom datasets get
    the full change-point machinery for free.
    """
    rng = np.random.default_rng(cfg.seed)
    T, C = change_points.shape
    N = cfg.sample_num
    x = rng.uniform(-1.0, 1.0, size=(C, T + 1, N, 2)).astype(np.float32)
    y = np.zeros((C, T + 1, N), dtype=np.int32)
    # step T is the held-out test slot: it continues the last concept
    concepts = np.concatenate([change_points, change_points[-1:]], axis=0)
    for c in range(C):
        for t in range(T + 1):
            theta = np.deg2rad(30.0 * concepts[t, c])
            rot = np.array([[np.cos(theta), -np.sin(theta)],
                            [np.sin(theta), np.cos(theta)]], dtype=np.float32)
            xr = x[c, t] @ rot.T
            y[c, t] = ((xr[:, 0] > 0) ^ (xr[:, 1] > 0)).astype(np.int32)
    return DriftDataset(x=x, y=y, num_classes=2, concepts=concepts,
                        name="xor-rot")


# ---------------------------------------------------------------------------
# 2. A custom model.
class TinyMlp(nn.Module):
    hidden: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(2)(x)


@register_model("tiny-mlp")
def make_tiny_mlp(ds: DriftDataset, cfg) -> nn.Module:
    return TinyMlp()


# ---------------------------------------------------------------------------
# 3. Any drift algorithm now composes with both.
def main(smoke: bool = False) -> float:
    from feddrift_tpu.simulation.runner import run_experiment

    cfg = ExperimentConfig(
        dataset="xor-rot", model="tiny-mlp",
        concept_drift_algo="softcluster",
        concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
        change_points="rand", drift_together=0,
        client_num_in_total=6, client_num_per_round=6,
        train_iterations=3 if smoke else 6,
        comm_round=10 if smoke else 40,
        epochs=5, batch_size=64, sample_num=64 if smoke else 256, lr=0.01,
        frequency_of_the_test=10, seed=3)
    exp = run_experiment(cfg)
    acc = float(exp.logger.last("Test/Acc"))
    print(f"FedDrift on custom dataset+model: final Test/Acc = {acc:.3f}")
    return acc


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
